"""Observability subsystem tests: trace context propagation (client →
gRPC metadata → service → BatchEntry → span ring buffer, stable across
retries), per-stage latency spans through the DynamicBatcher, the
metrics facade upgrade (labels, histogram count/sum reads, no-op
accumulation), structured JSON logs, the ``/tracez`` admin command, and
breaker transitions landing in the trace timeline.

The end-to-end test is the PR's acceptance criterion: a ``VerifyProof``
served through the batcher on CPU (conftest pins ``JAX_PLATFORMS=cpu``)
must yield a completed trace whose queue/device/host stage spans are all
recorded with non-negative durations, retrievable via the ring buffer
API, visible in ``/tracez``, and carrying the same trace id as the
structured JSON log line.
"""

import asyncio
import json
import logging

import grpc
import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.observability import (
    JsonLogFormatter,
    RequestContext,
    current_context,
    format_tracez,
    get_tracer,
)
from cpzk_tpu.observability.context import ATTEMPT_KEY, TRACE_ID_KEY
from cpzk_tpu.protocol.batch import (
    BatchVerifier,
    CpuBackend,
    FailoverBackend,
    VerifierBackend,
)
from cpzk_tpu.resilience.retry import RetryBudget, RetryPolicy
from cpzk_tpu.server import RateLimiter, ServerConfig, ServerState, metrics
from cpzk_tpu.server.__main__ import handle_command
from cpzk_tpu.server.batching import DynamicBatcher
from cpzk_tpu.server.service import AuthServiceImpl, make_generic_handler, serve

STAGES = {"queue_wait", "pad_and_pack", "device_dispatch", "unpack"}


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _fresh_tracer():
    tracer = get_tracer()
    prev_slow = tracer.slow_request_s
    tracer.clear()
    yield
    tracer.clear()
    tracer.slow_request_s = prev_slow


async def _register_and_prove(client, user, rng, params):
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    st = prover.statement
    resp = await client.register(
        user,
        Ristretto255.element_to_bytes(st.y1),
        Ristretto255.element_to_bytes(st.y2),
    )
    assert resp.success
    ch = await client.create_challenge(user)
    t = Transcript()
    t.append_context(bytes(ch.challenge_id))
    proof = prover.prove_with_transcript(rng, t)
    return bytes(ch.challenge_id), proof.to_bytes()


class _CaptureJson(logging.Handler):
    """Collects formatted JSON log lines."""

    def __init__(self):
        super().__init__()
        self.lines: list[str] = []
        self.setFormatter(JsonLogFormatter())

    def emit(self, record):
        self.lines.append(self.format(record))


# --- acceptance: end-to-end trace through the batcher -----------------------


def test_verify_proof_trace_end_to_end():
    """VerifyProof through DynamicBatcher: completed trace with all stage
    spans, /tracez visibility, and a JSON log line sharing the trace id."""
    tracer = get_tracer()
    tracer.slow_request_s = 0.0  # log every request
    capture = _CaptureJson()
    rpc_logger = logging.getLogger("cpzk_tpu.observability.rpc")
    rpc_logger.addHandler(capture)

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        batcher = DynamicBatcher(CpuBackend(), max_batch=64, window_ms=20.0)
        server, port = await serve(
            state, RateLimiter(10_000, 10_000),
            host="127.0.0.1", port=0, batcher=batcher,
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = [f"trace{i}" for i in range(3)]
                pairs = [
                    await _register_and_prove(client, u, rng, params)
                    for u in users
                ]
                resps = await asyncio.gather(
                    *[
                        client.verify_proof(u, cid, pf)
                        for u, (cid, pf) in zip(users, pairs)
                    ]
                )
                assert all(r.success for r in resps)
            return await handle_command("/tracez", state)
        finally:
            await batcher.stop()
            await server.stop(None)

    try:
        tracez_out, quit_ = run(main())
    finally:
        rpc_logger.removeHandler(capture)

    # -- ring buffer: every VerifyProof trace carries all four stages
    verify_traces = [
        t for t in tracer.completed() if t.name == "VerifyProof"
    ]
    assert len(verify_traces) == 3
    for tr in verify_traces:
        assert tr.status == "success"
        assert tr.duration_s > 0
        assert STAGES <= set(tr.span_names()), tr.span_names()
        for span in tr.spans:
            assert span.duration_s >= 0.0
        # queue_wait + device_dispatch + host stages all non-negative
        assert tr.stage_seconds("queue_wait") >= 0.0
        assert tr.stage_seconds("device_dispatch") >= 0.0
        host = tr.stage_seconds("pad_and_pack") + tr.stage_seconds("unpack")
        assert host >= 0.0

    # -- /tracez: the same traces are operator-visible
    assert not quit_
    assert "VerifyProof" in tracez_out
    for tr in verify_traces:
        assert tr.trace_id[:16] in tracez_out
    assert "device_dispatch=" in tracez_out

    # -- structured log: same trace id as the ring buffer records
    logged = [json.loads(line) for line in capture.lines]
    verify_logs = [l for l in logged if l.get("rpc") == "VerifyProof"]
    assert {l["trace_id"] for l in verify_logs} == {
        t.trace_id for t in verify_traces
    }
    for entry in verify_logs:
        assert entry["outcome"] == "success"
        assert entry["duration_ms"] >= 0
        assert "queue_wait" in entry["stages_ms"]

    # -- stage latency histograms observed on both planes
    count, total = metrics.read_histogram("tpu.batch.queue_wait")
    assert count >= 3 and total >= 0.0
    assert metrics.read_histogram("tpu.batch.host_time")[0] >= 1
    assert metrics.read_histogram(
        "tpu.batch.device_time", labels={"backend": "cpu"}
    )[0] >= 1


def test_trace_metadata_survives_retry():
    """Client-minted trace id arrives in gRPC metadata, stays stable
    across a PR-1 retry while the attempt number increments, and the
    final server-side trace records the retried attempt number."""
    tracer = get_tracer()
    seen: list[tuple[str | None, str | None]] = []

    class FlakyService(AuthServiceImpl):
        async def create_challenge(self, request, context):
            md = {k.lower(): v for k, v in context.invocation_metadata()}
            seen.append((md.get(TRACE_ID_KEY), md.get(ATTEMPT_KEY)))
            if len(seen) == 1:
                await context.abort(grpc.StatusCode.UNAVAILABLE, "flap")
            return await AuthServiceImpl.create_challenge(
                self, request, context
            )

    async def main():
        state = ServerState()
        service = FlakyService(state, RateLimiter(10_000, 10_000))
        server = grpc.aio.server()
        server.add_generic_rpc_handlers((make_generic_handler(service),))
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        policy = RetryPolicy(
            max_attempts=3,
            initial_backoff_s=0.001,
            max_backoff_s=0.002,
            budget=RetryBudget(tokens=10.0, token_ratio=0.1),
        )
        try:
            async with AuthClient(
                f"127.0.0.1:{port}", retry=policy
            ) as client:
                rng = SecureRng()
                params = Parameters.new()
                prover = Prover(
                    params, Witness(Ristretto255.random_scalar(rng))
                )
                st = prover.statement
                resp = await client.register(
                    "retryer",
                    Ristretto255.element_to_bytes(st.y1),
                    Ristretto255.element_to_bytes(st.y2),
                )
                assert resp.success
                ch = await client.create_challenge("retryer")
                assert ch.challenge_id
                return client.last_context
        finally:
            await server.stop(None)

    last_ctx = run(main())

    # two attempts hit the wire, same trace id, attempt bumped
    assert len(seen) == 2
    (tid1, a1), (tid2, a2) = seen
    assert tid1 and tid1 == tid2
    assert (a1, a2) == ("1", "2")
    assert last_ctx is not None
    assert last_ctx.trace_id == tid1 and last_ctx.attempt == 2

    # server-side ring: the successful attempt completed under the same
    # trace id with the retried attempt number
    challenge_traces = [
        t for t in tracer.completed()
        if t.name == "CreateChallenge" and t.trace_id == tid1
    ]
    assert challenge_traces
    assert challenge_traces[-1].attempt == 2
    assert challenge_traces[-1].status == "success"


def test_failure_paths_count_and_observe():
    """Early-abort paths count a failure AND observe the duration
    histogram (the boilerplate they used to skip)."""
    async def main():
        state = ServerState()
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), host="127.0.0.1", port=0
        )
        before_fail = metrics.read("auth.challenge.failure")
        before_obs = metrics.read_histogram("auth.challenge.duration")[0]
        before_labeled = metrics.read(
            "rpc.requests",
            labels={"rpc": "CreateChallenge", "outcome": "failure"},
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                with pytest.raises(grpc.RpcError):
                    await client.create_challenge("ghost-user")
        finally:
            await server.stop(None)
        return before_fail, before_obs, before_labeled

    before_fail, before_obs, before_labeled = run(main())
    assert metrics.read("auth.challenge.failure") == before_fail + 1
    assert metrics.read_histogram("auth.challenge.duration")[0] == before_obs + 1
    assert metrics.read(
        "rpc.requests", labels={"rpc": "CreateChallenge", "outcome": "failure"}
    ) == before_labeled + 1

    failed = [t for t in get_tracer().completed() if t.status == "failure"]
    assert any(t.name == "CreateChallenge" for t in failed)


# --- tracer unit behavior ----------------------------------------------------


def test_tracer_ring_capacity_and_find():
    tracer = get_tracer()
    tracer.configure(capacity=4)
    try:
        for i in range(10):
            ctx = RequestContext()
            tracer.start(ctx, f"op{i}")
            tracer.finish(ctx.trace_id, "success")
        completed = tracer.completed()
        assert len(completed) == 4
        assert [t.name for t in completed] == ["op6", "op7", "op8", "op9"]
        assert tracer.find(completed[-1].trace_id) == [completed[-1]]
    finally:
        tracer.configure(capacity=256)


def test_tracer_span_on_unknown_trace_is_dropped():
    tracer = get_tracer()
    tracer.add_span("no-such-trace", "queue_wait", 0.0, 1.0)
    tracer.add_span(None, "queue_wait", 0.0, 1.0)
    assert tracer.completed() == []


def test_format_tracez_empty_and_limit():
    assert "no completed traces" in format_tracez({"traces": []})
    tracer = get_tracer()
    for i in range(5):
        ctx = RequestContext()
        tracer.start(ctx, f"op{i}")
        tracer.finish(ctx.trace_id, "success")
    # the REPL renders the same payload the HTTP /tracez serves
    payload = tracer.payload()
    assert payload["schema"] == "cpzk-tracez/1"
    out = format_tracez(payload, limit=2)
    assert "op4" in out and "op3" in out and "op2" not in out


def test_breaker_transition_lands_in_trace_ring():
    """CLOSED→OPEN (and recovery) breaker flips are visible on the same
    timeline as request traces."""

    class Broken(VerifierBackend):
        prefers_combined = True

        def verify_combined(self, rows, beta):
            raise RuntimeError("injected device loss")

        def verify_each(self, rows):
            raise RuntimeError("injected device loss")

    rng = SecureRng()
    params = Parameters.new()
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    proof = prover.prove_with_transcript(rng, Transcript())

    backend = FailoverBackend(Broken(), CpuBackend())
    bv = BatchVerifier(backend=backend)
    bv.add(params, prover.statement, proof)
    bv.add(params, prover.statement, proof)
    assert bv.verify(rng) == [None, None]
    assert backend.degraded

    events = [
        t for t in get_tracer().completed() if t.name == "breaker_transition"
    ]
    assert events
    attrs = events[-1].spans[0].attrs
    assert (attrs["old"], attrs["new"]) == ("closed", "open")
    assert events[-1].status == "event"


# --- context plumbing --------------------------------------------------------


def test_request_context_metadata_roundtrip():
    ctx = RequestContext(attempt=3, parent_span="abcd")
    md = ctx.to_metadata()
    back = RequestContext.from_metadata(md, deadline=12.5)
    assert back.trace_id == ctx.trace_id
    assert back.attempt == 3
    assert back.parent_span == "abcd"
    assert back.deadline == 12.5


def test_request_context_tolerates_garbage_metadata():
    back = RequestContext.from_metadata(
        [(TRACE_ID_KEY, ""), (ATTEMPT_KEY, "not-a-number")]
    )
    assert back.trace_id  # freshly minted
    assert back.attempt == 1
    assert RequestContext.from_metadata(None).trace_id


def test_json_formatter_pulls_contextvar_trace_id():
    ctx = RequestContext()
    token = current_context.set(ctx)
    try:
        record = logging.LogRecord(
            "test", logging.INFO, __file__, 1, "hello %s", ("world",), None
        )
        data = json.loads(JsonLogFormatter().format(record))
    finally:
        current_context.reset(token)
    assert data["message"] == "hello world"
    assert data["trace_id"] == ctx.trace_id
    assert data["level"] == "INFO"
    assert data["logger"] == "test"


# --- metrics facade ----------------------------------------------------------


def test_noop_metric_observe_accumulates():
    from cpzk_tpu.server.metrics import _NoopMetric

    m = _NoopMetric()
    m.observe(0.5)
    m.observe(1.5)
    assert m._count.get() == 2.0
    assert m._sum.get() == 2.0


def test_noop_metric_labeled_children():
    from cpzk_tpu.server.metrics import _NoopMetric

    fam = _NoopMetric(("rpc", "outcome"))
    fam.labels(rpc="X", outcome="success").inc()
    fam.labels(rpc="X", outcome="success").inc(2)
    fam.labels(rpc="Y", outcome="failure").inc()
    assert fam.labels(rpc="X", outcome="success")._value.get() == 3.0
    assert fam.labels(rpc="Y", outcome="failure")._value.get() == 1.0


def test_histogram_read_count_and_sum():
    h = metrics.histogram("obs.test.hist")
    h.observe(0.25)
    h.observe(0.75)
    count, total = metrics.read_histogram("obs.test.hist")
    assert count == 2.0
    assert total == pytest.approx(1.0)
    assert metrics.read("obs.test.hist", "h") == pytest.approx(1.0)
    assert metrics.read_histogram("obs.test.never.created") == (0.0, 0.0)


def test_registered_inventory_lists_kinds():
    metrics.counter("obs.test.reg.counter").inc()
    metrics.gauge("obs.test.reg.gauge").set(1)
    pairs = metrics.registered()
    assert ("c", "obs.test.reg.counter") in pairs
    assert ("g", "obs.test.reg.gauge") in pairs


# --- config ------------------------------------------------------------------


def test_observability_config_env(monkeypatch):
    monkeypatch.setenv("SERVER_OBSERVABILITY_JSON_LOGS", "true")
    monkeypatch.setenv("SERVER_OBS_SLOW_REQUEST_MS", "250")
    monkeypatch.setenv("SERVER_OBSERVABILITY_TRACE_RING", "32")
    monkeypatch.setenv("SERVER_OBS_LATENCY_BUCKETS_MS", "1, 5, 10")
    cfg = ServerConfig()
    cfg._merge_env()
    assert cfg.observability.json_logs is True
    assert cfg.observability.slow_request_ms == 250.0
    assert cfg.observability.trace_ring == 32
    assert cfg.observability.parsed_buckets() == [0.001, 0.005, 0.01]
    cfg.validate()


def test_observability_config_validation():
    cfg = ServerConfig()
    cfg.observability.trace_ring = 0
    with pytest.raises(ValueError):
        cfg.validate()
    cfg = ServerConfig()
    cfg.observability.slow_request_ms = -5
    with pytest.raises(ValueError):
        cfg.validate()
    cfg = ServerConfig()
    cfg.observability.latency_buckets_ms = "10,5,1"
    with pytest.raises(ValueError):
        cfg.validate()
    cfg = ServerConfig()
    cfg.observability.latency_buckets_ms = "abc"
    with pytest.raises(ValueError):
        cfg.validate()


def test_configure_applies_settings():
    from cpzk_tpu.observability import configure
    from cpzk_tpu.server.config import ObservabilitySettings

    tracer = get_tracer()
    try:
        configure(ObservabilitySettings(slow_request_ms=-1, trace_ring=8))
        assert tracer.slow_request_s is None
        configure(ObservabilitySettings(slow_request_ms=500, trace_ring=8))
        assert tracer.slow_request_s == 0.5
    finally:
        tracer.configure(capacity=256, slow_request_s=1.0)


# --- /tracez command ---------------------------------------------------------


def test_tracez_command_empty_and_bad_arg():
    async def main():
        state = ServerState()
        out_empty, _ = await handle_command("/tracez", state)
        out_bad, _ = await handle_command("/tracez banana", state)
        return out_empty, out_bad

    out_empty, out_bad = run(main())
    assert "no completed traces" in out_empty
    assert "usage: /tracez" in out_bad
