"""Native wire path (server/wire.py + native/wire.cpp): the C++ request
parser must be OBSERVATIONALLY IDENTICAL to the protobuf runtime — same
field values on accepted messages, unconditional fallback for anything
else, byte-identical per-entry verdicts through the service layer — and
the packed-proof staging buffer must change where work happens, never
what it computes.
"""

import asyncio
import dataclasses
import os
import pathlib
import re
import subprocess
import sys

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.protocol.gadgets import PROOF_WIRE_SIZE, Proof
from cpzk_tpu.server import RateLimiter, ServerState, wire as wire_mod
from cpzk_tpu.server.config import ServerConfig, ServerSettings
from cpzk_tpu.server.proto import load_pb2
from cpzk_tpu.server.service import request_deserializers, serve

ROOT = pathlib.Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(
    not wire_mod.native_available(),
    reason="native core unavailable (no C++ toolchain)",
)


def run(coro):
    return asyncio.run(coro)


def _deser(rpc):
    return request_deserializers(load_pb2(), "native")[rpc]


# --- view parity -------------------------------------------------------------


def test_batch_view_parity_with_protobuf():
    pb2 = load_pb2()
    req = pb2.BatchVerificationRequest(
        user_ids=[f"user{i}" for i in range(50)] + ["héllo-ü"],
        challenge_ids=[bytes([i]) * 33 for i in range(51)],
        proofs=[bytes([i]) * PROOF_WIRE_SIZE for i in range(51)],
    )
    data = req.SerializeToString()
    view = _deser("VerifyProofBatch")(data)
    assert isinstance(view, wire_mod.NativeBatchVerificationRequest)
    ref = pb2.BatchVerificationRequest.FromString(data)
    assert view.user_ids == list(ref.user_ids)
    assert view.challenge_ids == list(ref.challenge_ids)
    assert view.proofs == list(ref.proofs)
    # the zero-copy payoff: the C-gathered buffer IS the concatenation
    assert view.proofs_packed == b"".join(ref.proofs)
    assert view.packed_proofs(51) == view.proofs_packed
    assert view.packed_proofs(50) is None  # subset: no contiguity claim


def test_stream_view_parity_with_protobuf():
    pb2 = load_pb2()
    req = pb2.StreamVerifyRequest(
        ids=[0, 1, 2**63, 7],
        user_ids=["a", "b", "c", "d"],
        challenge_ids=[b"x" * 33] * 4,
        proofs=[bytes(PROOF_WIRE_SIZE)] * 4,
        mint_sessions=True,
    )
    data = req.SerializeToString()
    view = _deser("VerifyProofStream")(data)
    assert isinstance(view, wire_mod.NativeStreamVerifyRequest)
    ref = pb2.StreamVerifyRequest.FromString(data)
    assert view.ids == list(ref.ids)
    assert view.user_ids == list(ref.user_ids)
    assert view.challenge_ids == list(ref.challenge_ids)
    assert view.proofs == list(ref.proofs)
    assert view.mint_sessions is True
    # unpacked (wiretype-0) ids are legal proto3 too: field 1, varint
    unpacked = b"\x08\x05\x08\x2a" + data
    view2 = _deser("VerifyProofStream")(unpacked)
    ref2 = pb2.StreamVerifyRequest.FromString(unpacked)
    assert view2.ids == list(ref2.ids)


def test_challenge_view_parity_with_protobuf():
    pb2 = load_pb2()
    for uid in ("alice", "héllo-ü", "", "a" * 300):
        data = pb2.ChallengeRequest(user_id=uid).SerializeToString()
        view = _deser("CreateChallenge")(data)
        assert isinstance(view, wire_mod.NativeChallengeRequest)
        assert view.user_id == pb2.ChallengeRequest.FromString(data).user_id
    # duplicated singular field: last occurrence wins, like proto3
    twice = (pb2.ChallengeRequest(user_id="first").SerializeToString()
             + pb2.ChallengeRequest(user_id="second").SerializeToString())
    assert _deser("CreateChallenge")(twice).user_id == "second"
    assert pb2.ChallengeRequest.FromString(twice).user_id == "second"


def test_parser_punts_outside_its_subset():
    """Unknown fields, foreign wire types, and invalid UTF-8 all fall
    back to the protobuf runtime — same accept/reject, same errors."""
    pb2 = load_pb2()
    deser = _deser("CreateChallenge")
    base = pb2.ChallengeRequest(user_id="u").SerializeToString()
    # unknown field number: protobuf accepts (unknown-field set); the
    # native parser punts, so the result is the protobuf message itself
    unknown = base + b"\x22\x01x"  # field 4, LEN
    got = deser(unknown)
    assert type(got).__name__ == "ChallengeRequest"
    assert got.user_id == "u"
    # invalid UTF-8 in a string field: both paths reject identically
    bad_utf8 = b"\x0a\x02\xff\xfe"
    with pytest.raises(Exception) as native_err:
        deser(bad_utf8)
    with pytest.raises(Exception) as py_err:
        pb2.ChallengeRequest.FromString(bad_utf8)
    assert type(native_err.value) is type(py_err.value)
    # truncated varint / garbage: both reject
    for garbage in (b"\x0a", b"\x0a\xff", b"\x80" * 12, b"\x0a\x7fzz"):
        try:
            ref = pb2.ChallengeRequest.FromString(garbage)
        except Exception:
            with pytest.raises(Exception):
                deser(garbage)
        else:
            got = deser(garbage)
            assert got.user_id == ref.user_id


def test_packed_proofs_none_when_sizes_vary():
    pb2 = load_pb2()
    req = pb2.BatchVerificationRequest(
        user_ids=["a", "b"], challenge_ids=[b"c" * 33] * 2,
        proofs=[bytes(PROOF_WIRE_SIZE), b"short"],
    )
    view = _deser("VerifyProofBatch")(req.SerializeToString())
    assert view.proofs_packed is None
    assert view.proofs == [bytes(PROOF_WIRE_SIZE), b"short"]


# --- packed parse equivalence ------------------------------------------------


def _proof_corpus():
    rng = SecureRng()
    params = Parameters.new()
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    t = Transcript()
    t.append_context(b"ctx")
    wire = prover.prove_with_transcript(rng, t).to_bytes()
    return [
        wire,
        wire[:5] + b"\xff" * 32 + wire[37:],   # invalid r1 point
        wire[:77] + bytes(32),                 # zero scalar
        b"\x02" + wire[1:],                    # bad version
        wire,
    ]


def test_from_bytes_batch_packed_equivalence():
    items = _proof_corpus()
    assert all(len(i) == PROOF_WIRE_SIZE for i in items)
    packed = b"".join(items)
    for defer in (False, True):
        plain = Proof.from_bytes_batch(items, defer_point_validation=defer)
        fast = Proof.from_bytes_batch(
            items, defer_point_validation=defer, packed=packed)
        for a, b in zip(plain, fast, strict=True):
            if isinstance(a, Proof):
                assert isinstance(b, Proof)
                assert a.to_bytes() == b.to_bytes()
                assert a.deferred == b.deferred
            else:
                assert type(a) is type(b) and str(a) == str(b)
    # a mismatched packed buffer is ignored, never trusted
    wrong = packed[:-1]
    safe = Proof.from_bytes_batch(items, packed=wrong)
    assert all(
        type(x) is type(y) for x, y in zip(
            safe, Proof.from_bytes_batch(items), strict=True)
    )


# --- service-layer parity (the satellite-3 pin) ------------------------------


async def _serve_and_verify_mixed(wire: str):
    """One coalesced batch with malformed wires through a real server at
    the given wire mode; returns (per-entry (success, message) list,
    stream verdict list, transport counters delta is asserted by the
    caller)."""
    rng = SecureRng()
    params = Parameters.new()
    provers = [Prover(params, Witness(Ristretto255.random_scalar(rng)))
               for _ in range(4)]
    eb = Ristretto255.element_to_bytes
    state = ServerState()
    server, port = await serve(
        state, RateLimiter(10**9, 10**9), port=0, wire=wire)
    try:
        async with AuthClient(f"127.0.0.1:{port}") as client:
            resp = await client.register_batch(
                [f"u{i}" for i in range(4)],
                [eb(p.statement.y1) for p in provers],
                [eb(p.statement.y2) for p in provers],
            )
            assert all(r.success for r in resp.results)

            async def wave():
                ids, cids, proofs = [], [], []
                for i, p in enumerate(provers):
                    ch = await client.create_challenge(f"u{i}")
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    ids.append(f"u{i}")
                    cids.append(cid)
                    proofs.append(p.prove_with_transcript(rng, t).to_bytes())
                return ids, cids, proofs

            ids, cids, proofs = await wave()
            # malformed wires INSIDE the coalesced batch: truncated,
            # bad point, zero scalar, plus one valid
            proofs[1] = proofs[1][:50]
            proofs[2] = proofs[2][:5] + b"\xff" * 32 + proofs[2][37:]
            resp = await client.verify_proof_batch(ids, cids, proofs)
            batch_out = [(r.success, r.message) for r in resp.results]

            ids, cids, proofs = await wave()
            proofs[0] = b""
            proofs[3] = proofs[3] + b"\x00"
            entries = list(zip(ids, cids, proofs))
            stream_out = []
            async for chunk in client.verify_proof_stream_chunks(
                entries, chunk=4
            ):
                stream_out.append((list(chunk[0]), list(chunk[1]),
                                   list(chunk[2])))
            return batch_out, stream_out
    finally:
        await server.stop(None)


def test_malformed_batch_parity_native_vs_python():
    """Satellite 3: a coalesced batch containing malformed wires answers
    IDENTICALLY (per-entry verdicts and messages) through the native
    wire path and the Python protobuf path."""
    native = run(_serve_and_verify_mixed("native"))
    python = run(_serve_and_verify_mixed("python"))
    assert native == python
    batch_out, stream_out = native
    assert batch_out[0][0] is True
    assert batch_out[1] == (
        False, "Invalid proof: Truncated proof: incomplete r2 data")
    assert batch_out[2][0] is False  # deferred decode failure, exact msg
    assert "Invalid proof" in batch_out[2][1]
    assert batch_out[3][0] is True
    (ids, oks, msgs), = stream_out
    assert oks == [False, True, True, False]
    assert msgs[0] == "Empty proof"
    assert msgs[3] == "Invalid proof: Proof has 1 trailing bytes"


def test_native_counters_and_span(tmp_path):
    from cpzk_tpu.server import metrics

    before = metrics.read(
        "transport.parse.native", labels={"rpc": "VerifyProofBatch"})
    run(_serve_and_verify_mixed("native"))
    after = metrics.read(
        "transport.parse.native", labels={"rpc": "VerifyProofBatch"})
    assert after > before


def test_python_mode_never_builds_views():
    desers = request_deserializers(load_pb2(), "python")
    pb2 = load_pb2()
    req = pb2.ChallengeRequest(user_id="u").SerializeToString()
    assert type(desers["CreateChallenge"](req)).__name__ == "ChallengeRequest"


def test_fallback_when_native_unavailable(monkeypatch):
    monkeypatch.setattr(wire_mod, "native_available", lambda: False)
    desers = request_deserializers(load_pb2(), "native")
    pb2 = load_pb2()
    req = pb2.ChallengeRequest(user_id="u").SerializeToString()
    assert type(desers["CreateChallenge"](req)).__name__ == "ChallengeRequest"


_NO_NATIVE_SCRIPT = """
import asyncio, os
# simulate a box with no buildable native core: the .so path is empty
# and CPZK_NO_NATIVE_BUILD forbids building one
import cpzk_tpu.core._native as native
native._LIB_PATH = os.path.join("%s", "missing.so")
native._tried = False

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server import wire as wire_mod
from cpzk_tpu.server.service import serve

assert not wire_mod.native_available()

async def main():
    rng = SecureRng(); params = Parameters.new()
    p = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    eb = Ristretto255.element_to_bytes
    server, port = await serve(
        ServerState(), RateLimiter(10**9, 10**9), port=0, wire="native")
    async with AuthClient(f"127.0.0.1:{port}") as c:
        r = await c.register("u", eb(p.statement.y1), eb(p.statement.y2))
        assert r.success
        ch = await c.create_challenge("u")
        cid = bytes(ch.challenge_id)
        t = Transcript(); t.append_context(cid)
        resp = await c.verify_proof(
            "u", cid, p.prove_with_transcript(rng, t).to_bytes())
        assert resp.success
        ch = await c.create_challenge("u")
        resp = await c.verify_proof_batch(["u"], [bytes(ch.challenge_id)],
                                          [b"zz"])
        assert resp.results[0].message == \\
            "Invalid proof: Proof too small: 2 bytes", resp.results[0].message
    await server.stop(None)
    print("NO-NATIVE-OK")

asyncio.run(main())
"""


def test_no_native_build_env_serves_identically(tmp_path):
    """Acceptance: with CPZK_NO_NATIVE_BUILD=1 (and no .so) the wire
    path falls back to the Python parse with no behavioral difference."""
    env = dict(os.environ)
    env["CPZK_NO_NATIVE_BUILD"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    result = subprocess.run(
        [sys.executable, "-c", _NO_NATIVE_SCRIPT % tmp_path],
        capture_output=True, text=True, cwd=str(ROOT), env=env, timeout=120,
    )
    assert result.returncode == 0, result.stderr
    assert "NO-NATIVE-OK" in result.stdout


def test_build_failure_warns_once(tmp_path, monkeypatch, caplog):
    """Satellite 1: a failing native build logs ONE WARNING carrying the
    compiler stderr instead of being swallowed silently."""
    import logging

    import cpzk_tpu.core._native as native

    monkeypatch.setattr(native, "_build_warned", False)
    monkeypatch.setattr(native, "_SRC_DIR", str(tmp_path))  # no Makefile
    monkeypatch.delenv("CPZK_NO_NATIVE_BUILD", raising=False)
    with caplog.at_level(logging.WARNING, logger="cpzk_tpu.core.native"):
        assert native._build() is False
        assert native._build() is False  # second failure: no second warn
    warnings = [r for r in caplog.records if "native core build failed" in r.message]
    assert len(warnings) == 1
    assert "make -C" in warnings[0].message
    # the deliberate opt-out stays silent (distinguishable by design)
    caplog.clear()
    monkeypatch.setattr(native, "_build_warned", False)
    monkeypatch.setenv("CPZK_NO_NATIVE_BUILD", "1")
    with caplog.at_level(logging.WARNING, logger="cpzk_tpu.core.native"):
        assert native._build() is False
    assert not [r for r in caplog.records if "build failed" in r.message]


# --- perf-gate wire key ------------------------------------------------------


def test_perf_entry_wire_is_a_config_key(tmp_path):
    """Satellite 4: ``wire`` is a perf-gate config-key component — old
    baselines load as ``wire="python"`` (exactly what they measured),
    native-wire entries never gate against them (only_new seeds the
    trajectory), and the field serializes only when != python."""
    import json
    import pathlib

    from cpzk_tpu.observability.perf import (
        PerfEntry,
        compare_entries,
        load_snapshot,
        write_snapshot,
    )

    old = [PerfEntry("e2e_curve.stream", "cpu", 65536, 2815.0, "proofs/s")]
    new = [
        PerfEntry("e2e_curve.stream", "cpu", 65536, 2800.0, "proofs/s"),
        PerfEntry("e2e_curve.stream", "cpu", 65536, 10.0, "proofs/s",
                  wire="native"),
    ]
    report = compare_entries(old, new, threshold=0.35)
    assert report["passed"], report  # the native entry is only_new
    assert report["only_new"] == [
        ("e2e_curve.stream", "cpu", 65536, "proofs/s", 1, "native")
    ]
    path = str(tmp_path / "snap.json")
    write_snapshot(path, new)
    loaded = load_snapshot(path)
    assert sorted(e.key() for e in loaded) == sorted(e.key() for e in new)
    raw = json.loads(pathlib.Path(path).read_text())
    assert sorted(
        (e.get("wire") for e in raw["entries"]), key=str
    ) == [None, "native"]


# --- [server] config knobs ---------------------------------------------------


def test_server_config_layering_and_validation(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = ServerConfig.from_env()
    assert cfg.server.wire == "native"
    assert cfg.server.ingest_shards == 1

    (tmp_path / "server.toml").write_text(
        '[server]\nwire = "python"\ningest_shards = 4\n'
    )
    monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
    cfg = ServerConfig.from_env()
    assert cfg.server.wire == "python"
    assert cfg.server.ingest_shards == 4
    cfg.validate()
    monkeypatch.setenv("SERVER_WIRE", "NATIVE")
    monkeypatch.setenv("SERVER_INGEST_SHARDS", "2")
    cfg = ServerConfig.from_env()
    assert cfg.server.wire == "native"
    assert cfg.server.ingest_shards == 2

    bad = ServerConfig()
    bad.server.wire = "rust"
    with pytest.raises(ValueError, match="server.wire"):
        bad.validate()
    bad = ServerConfig()
    bad.server.ingest_shards = 0
    with pytest.raises(ValueError, match="ingest_shards"):
        bad.validate()
    bad = ServerConfig()
    bad.server.ingest_shards = 65
    with pytest.raises(ValueError, match="ingest_shards"):
        bad.validate()
    # ingest shards proxy only auth + health: a standby must listen itself
    bad = ServerConfig()
    bad.server.ingest_shards = 2
    bad.state_file = "/tmp/x.json"
    bad.durability.enabled = True
    bad.replication.enabled = True
    bad.replication.role = "standby"
    with pytest.raises(ValueError, match="ingest_shards"):
        bad.validate()


def test_server_config_keys_documented():
    """CI drift guard: every [server] knob ships in the TOML example, the
    .env example, and the operations-doc knob inventory."""
    keys = [f.name for f in dataclasses.fields(ServerSettings)]
    assert keys

    toml_text = (ROOT / "config" / "server.toml.example").read_text()
    m = re.search(r"^\[server\]$", toml_text, re.M)
    assert m, "[server] section missing from config/server.toml.example"
    section = toml_text[m.end():].split("\n[", 1)[0]
    env_text = (ROOT / ".env.example").read_text()
    docs = (ROOT / "docs" / "operations.md").read_text()
    for key in keys:
        assert re.search(rf"^{key}\s*=", section, re.M), (
            f"[server] key {key!r} missing from config/server.toml.example"
        )
        assert f"SERVER_{key.upper()}" in env_text, (
            f"SERVER_{key.upper()} missing from .env.example"
        )
        assert f"`server.{key}`" in docs, (
            f"`server.{key}` missing from the docs/operations.md "
            "knob inventory"
        )
