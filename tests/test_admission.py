"""Admission subsystem: per-client fair limiting, priority-aware adaptive
shedding, server retry-pushback, and the readiness split.

Unit layers use injected clocks and signal providers, so every AIMD move
is exact; the gRPC layers assert the wire contract — every
RESOURCE_EXHAUSTED carries ``cpzk-retry-after-ms`` trailing metadata, and
the client retry policy sleeps exactly the advertised pushback instead of
its own jitter (gRFC A6).  The overload-storm acceptance scenario lives
in ``tests/test_chaos.py``.
"""

import asyncio
import dataclasses
import pathlib
import random
import re
import types

import grpc
import pytest

from cpzk_tpu.admission import (
    MIN_LEVEL,
    RETRY_PUSHBACK_KEY,
    AdmissionController,
    KeyedTokenBuckets,
    classify,
    client_key,
)
from cpzk_tpu.client import AuthClient
from cpzk_tpu.resilience.retry import MAX_PUSHBACK_S, RetryBudget, RetryPolicy
from cpzk_tpu.server import RateLimiter, ServerState, metrics
from cpzk_tpu.server.config import AdmissionSettings, ServerConfig
from cpzk_tpu.server.service import serve

ROOT = pathlib.Path(__file__).resolve().parent.parent


def run(coro):
    return asyncio.run(coro)


# --- keyed token buckets -----------------------------------------------------


def test_keyed_buckets_admit_burst_then_throttle_and_refill():
    t = [0.0]
    kb = KeyedTokenBuckets(60, burst=3, max_keys=8, clock=lambda: t[0])
    assert [kb.check("a") for _ in range(3)] == [None] * 3
    retry = kb.check("a")
    assert retry is not None and retry == pytest.approx(1.0)
    # another key is unaffected — fairness is the whole point
    assert kb.check("b") is None
    # refill at 1 token/s
    t[0] = 1.0
    assert kb.check("a") is None
    assert kb.check("a") is not None


def test_keyed_buckets_lru_bound_and_disabled_mode():
    t = [0.0]
    kb = KeyedTokenBuckets(60, burst=1, max_keys=4, clock=lambda: t[0])
    for i in range(100):
        kb.check(f"key-{i}")
    assert len(kb) == 4
    assert kb.evictions == 96
    # most-recently-seen keys survive
    kb2 = KeyedTokenBuckets(60, burst=5, max_keys=2, clock=lambda: t[0])
    kb2.check("old"), kb2.check("mid"), kb2.check("old"), kb2.check("new")
    assert len(kb2) == 2
    assert kb2.check("old") is None  # still tracked (burst not exhausted)

    # rpm=0: disabled — admits everything, allocates nothing
    off = KeyedTokenBuckets(0, burst=1, max_keys=2, clock=lambda: t[0])
    for i in range(50):
        assert off.check(f"k{i}") is None
    assert len(off) == 0 and not off.enabled


def test_client_key_prefers_metadata_tag_then_peer_host():
    class Ctx:
        def __init__(self, md, peer):
            self._md, self._peer = md, peer

        def invocation_metadata(self):
            return self._md

        def peer(self):
            return self._peer

    assert client_key(Ctx([("cpzk-client-id", "alice")], "ipv4:1.2.3.4:55")) == "id:alice"
    assert client_key(Ctx([("CPZK-Client-Id", b"bob")], "")) == "id:bob"
    # peer fallback strips the ephemeral port: connection churn must not
    # mint fresh buckets
    assert client_key(Ctx([], "ipv4:1.2.3.4:55001")) == "peer:ipv4:1.2.3.4"
    assert client_key(Ctx([], "ipv6:[::1]:55001")) == "peer:ipv6:[::1]"
    assert client_key(Ctx([], "unix:/tmp/s.sock")) == "peer:unix:/tmp/s.sock"
    # hostile metadata is truncated, never raises
    key = client_key(Ctx([("cpzk-client-id", "x" * 4096)], ""))
    assert len(key) <= 128
    assert client_key(object()) == "peer:unknown"


# --- classification + adaptive controller ------------------------------------


def test_classify_tiers_and_totality():
    assert classify("VerifyProof") == 0 == classify("VerifyProofBatch")
    assert classify("CreateChallenge") == 1
    assert classify("Register") == 2 == classify("RegisterBatch")
    for junk in ("", "Nope", None, 42, b"\xff\x00", object()):
        assert classify(junk) == 2  # unknown -> lowest priority, no raise


def _controller(signals, clock, **kw):
    kw.setdefault("per_client_rpm", 0)
    kw.setdefault("adjust_interval_ms", 10.0)
    kw.setdefault("increase_step", 0.5)
    kw.setdefault("decrease_factor", 0.5)
    return AdmissionController(
        AdmissionSettings(**kw), clock=clock, signals=signals
    )


def test_aimd_sheds_lowest_tier_first_and_recovers():
    t = [0.0]
    sig = [(0.0, 0.0)]
    c = _controller(lambda: sig[0], lambda: t[0])
    assert c.level == 3.0
    # healthy: everything admitted
    for rpc in ("Register", "CreateChallenge", "VerifyProof"):
        assert c.admit(rpc, "k") is None

    # overload tick 1: 3.0 -> 1.5, register sheds, challenge+verify pass
    sig[0] = (0.95, 0.0)
    t[0] += 0.011
    r = c.admit("Register", "k")
    assert r is not None and r.reason == "priority" and c.level == 1.5
    assert c.admit("CreateChallenge", "k") is None
    assert c.admit("VerifyProof", "k") is None

    # overload tick 2: 1.5 -> floor 1.0, challenge sheds too, verify NEVER
    t[0] += 0.011
    r = c.admit("CreateChallenge", "k")
    assert r is not None and r.reason == "priority" and c.level == MIN_LEVEL
    for _ in range(5):
        t[0] += 0.011
        assert c.admit("VerifyProof", "k") is None  # floor holds forever
    assert c.level == MIN_LEVEL

    # recovery: additive climb at increase_step per healthy tick
    sig[0] = (0.1, 0.0)
    t[0] += 0.011
    c.admit("VerifyProof", "k")
    assert c.level == pytest.approx(1.5)
    t[0] += 0.011
    c.admit("CreateChallenge", "k")  # 1.5 -> 2.0 then tier1 < 2.0 admitted
    assert c.level == pytest.approx(2.0)
    # same interval (no clock advance): tier2 not yet readmitted at 2.0
    assert c.admit("Register", "k") is not None
    t[0] += 0.011
    assert c.admit("Register", "k") is None  # level 2.5: tier2 back
    assert c.level == pytest.approx(2.5)
    t[0] += 0.011
    c.admit("Register", "k")
    assert c.level == pytest.approx(3.0)  # fully recovered, capped at 3


def test_queue_wait_signal_alone_triggers_shedding():
    t = [0.0]
    sig = [(0.0, 0.0)]
    c = _controller(lambda: sig[0], lambda: t[0], target_queue_wait_ms=50.0)
    sig[0] = (0.0, 0.2)  # low depth, but 200ms avg queue wait
    t[0] += 0.011
    r = c.admit("Register", "k")
    assert r is not None and r.reason == "priority"


def test_hysteresis_band_freezes_level():
    t = [0.0]
    sig = [(0.6, 0.0)]  # between low (0.5) and high (0.75) watermarks
    c = _controller(lambda: sig[0], lambda: t[0])
    c.level = 2.0
    for _ in range(5):
        t[0] += 0.011
        c.admit("VerifyProof", "k")
    assert c.level == 2.0  # neither overloaded nor healthy: no movement


def test_per_client_bucket_checked_before_priority():
    t = [0.0]
    c = _controller(
        lambda: (0.0, 0.0), lambda: t[0],
        per_client_rpm=60, per_client_burst=1,
    )
    assert c.admit("VerifyProof", "hot") is None
    r = c.admit("VerifyProof", "hot")
    assert r is not None and r.reason == "per_client"
    assert r.retry_after_s >= c.settings.retry_after_min_ms / 1000.0
    assert c.admit("VerifyProof", "cold") is None  # others unaffected


def test_retry_after_sized_from_drain_rate():
    class FakeBatcher:
        window = 0.005
        max_batch = 64

        def __init__(self, depth, cap, rate):
            self._snap, self._rate = (depth, cap), rate

        def load_snapshot(self):
            return self._snap

        def drain_rate(self):
            return self._rate

    t = [0.0]
    s = AdmissionSettings(retry_after_min_ms=10, retry_after_max_ms=2000)
    # 100 queued, draining 200/s -> 500ms
    c = AdmissionController(s, batcher=FakeBatcher(100, 256, 200.0),
                            clock=lambda: t[0], signals=lambda: (0, 0))
    assert c.retry_after_s() == pytest.approx(0.5)
    # clamped into [min, max]
    c = AdmissionController(s, batcher=FakeBatcher(1, 256, 1e6),
                            clock=lambda: t[0], signals=lambda: (0, 0))
    assert c.retry_after_s() == pytest.approx(0.010)
    c = AdmissionController(s, batcher=FakeBatcher(10**6, 256, 1.0),
                            clock=lambda: t[0], signals=lambda: (0, 0))
    assert c.retry_after_s() == pytest.approx(2.0)
    # no batcher: the configured floor
    c = AdmissionController(s, clock=lambda: t[0], signals=lambda: (0, 0))
    assert c.retry_after_s() == pytest.approx(0.010)


# --- retry pushback (gRFC A6) ------------------------------------------------


def test_policy_sleep_prefers_pushback_over_jitter():
    pol = RetryPolicy(initial_backoff_s=0.05, max_backoff_s=1.0)
    rng = random.Random(0)
    # pushback overrides the computed jitter exactly
    assert pol.sleep_s(1, pushback_ms=123.0, rng=rng) == pytest.approx(0.123)
    assert pol.sleep_s(5, pushback_ms=0.0, rng=rng) == 0.0
    # hostile pushback is capped
    assert pol.sleep_s(1, pushback_ms=10**9, rng=rng) == MAX_PUSHBACK_S
    # absent pushback falls back to full jitter within the attempt cap
    for _ in range(50):
        assert 0.0 <= pol.sleep_s(1, rng=rng) <= 0.05


class PushbackRpcError(grpc.RpcError):
    def __init__(self, code, trailing=()):
        self._code, self._trailing = code, trailing

    def code(self):
        return self._code

    def trailing_metadata(self):
        return self._trailing


def _sleep_recorder(monkeypatch, module):
    sleeps = []

    async def fake_sleep(s):
        sleeps.append(s)

    monkeypatch.setattr(
        module, "asyncio",
        types.SimpleNamespace(sleep=fake_sleep),
    )
    return sleeps


def test_client_honors_pushback_and_budget(monkeypatch):
    import cpzk_tpu.client.rpc as rpc_mod

    sleeps = _sleep_recorder(monkeypatch, rpc_mod)

    async def main():
        state = ServerState()
        server, port = await serve(state, RateLimiter(10_000, 10_000), port=0)
        try:
            client = AuthClient(
                f"127.0.0.1:{port}",
                retry=RetryPolicy(
                    max_attempts=4, initial_backoff_s=5.0, max_backoff_s=9.0
                ),
                retry_rng=random.Random(1),
            )
            async with client:
                calls = {"n": 0}
                md = ((RETRY_PUSHBACK_KEY, "217"),)

                async def shed_twice(request, timeout=None, metadata=None):
                    calls["n"] += 1
                    if calls["n"] <= 2:
                        raise PushbackRpcError(
                            grpc.StatusCode.RESOURCE_EXHAUSTED, md
                        )
                    return object()

                client._stubs["CreateChallenge"] = shed_twice
                await client.create_challenge("someone")
                # the sleeps are EXACTLY the advertised pushback — with
                # jitter they would be uniform on [0, 5s]/[0, 9s]
                assert sleeps == [0.217, 0.217]
                assert calls["n"] == 3

                # negative pushback: server said do not retry
                calls["n"] = 0
                sleeps.clear()

                async def shed_forever(request, timeout=None, metadata=None):
                    calls["n"] += 1
                    raise PushbackRpcError(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        ((RETRY_PUSHBACK_KEY, "-1"),),
                    )

                client._stubs["CreateChallenge"] = shed_forever
                with pytest.raises(grpc.RpcError):
                    await client.create_challenge("someone")
                assert calls["n"] == 1 and sleeps == []

                # pushback does NOT bypass the retry budget
                calls["n"] = 0
                sleeps.clear()
                client.retry = RetryPolicy(
                    max_attempts=10,
                    initial_backoff_s=0.001, max_backoff_s=0.002,
                    budget=RetryBudget(tokens=2.0, token_ratio=0.0),
                )

                async def shed_with_pushback(request, timeout=None, metadata=None):
                    calls["n"] += 1
                    raise PushbackRpcError(
                        grpc.StatusCode.RESOURCE_EXHAUSTED, md
                    )

                client._stubs["CreateChallenge"] = shed_with_pushback
                with pytest.raises(grpc.RpcError):
                    await client.create_challenge("someone")
                assert calls["n"] == 3  # initial + 2 budgeted retries
                assert sleeps == [0.217, 0.217]
        finally:
            await server.stop(None)

    run(main())


def test_every_resource_exhausted_path_carries_pushback():
    """Satellite: the global rate limit (and by the same helper, the
    challenge-cap and queue-full paths) attaches cpzk-retry-after-ms."""

    async def main():
        state = ServerState()
        # burst 1: the second immediate RPC trips the global bucket
        server, port = await serve(state, RateLimiter(60, 1), port=0)
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                # first call consumes the only token (NOT_FOUND is fine —
                # admission happens before the user lookup)
                with pytest.raises(grpc.RpcError):
                    await client.create_challenge("nobody")
                try:
                    await client.create_challenge("nobody")
                    raise AssertionError("expected RESOURCE_EXHAUSTED")
                except grpc.RpcError as e:
                    assert e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                    trailing = dict(
                        (str(k).lower(), v) for k, v in e.trailing_metadata()
                    )
                    assert RETRY_PUSHBACK_KEY in trailing
                    assert float(trailing[RETRY_PUSHBACK_KEY]) >= 0
        finally:
            await server.stop(None)

    run(main())


def test_admission_rejection_carries_pushback_over_grpc():
    async def main():
        state = ServerState()
        controller = AdmissionController(
            AdmissionSettings(per_client_rpm=60, per_client_burst=1)
        )
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), port=0, admission=controller
        )
        try:
            async with AuthClient(
                f"127.0.0.1:{port}", client_id="hot-client"
            ) as client:
                with pytest.raises(grpc.RpcError):  # NOT_FOUND, admitted
                    await client.create_challenge("nobody")
                try:
                    await client.create_challenge("nobody")
                    raise AssertionError("expected RESOURCE_EXHAUSTED")
                except grpc.RpcError as e:
                    assert e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                    assert "Per-client" in e.details()
                    trailing = dict(
                        (str(k).lower(), v) for k, v in e.trailing_metadata()
                    )
                    assert float(trailing[RETRY_PUSHBACK_KEY]) >= 0
                # the metadata tag keyed the bucket: same host, different
                # id, fresh bucket
                async with AuthClient(
                    f"127.0.0.1:{port}", client_id="polite-client"
                ) as other:
                    with pytest.raises(grpc.RpcError) as ei:
                        await other.create_challenge("nobody")
                    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
        finally:
            await server.stop(None)

    run(main())


# --- readiness split ---------------------------------------------------------


def test_readiness_not_serving_while_degraded_or_recovering():
    from cpzk_tpu.protocol.batch import CpuBackend, FailoverBackend
    from cpzk_tpu.resilience.faults import FaultInjectionBackend, FaultPlan
    from cpzk_tpu.server.proto import load_health_pb2

    async def main():
        hpb2 = load_health_pb2()
        SERVING = hpb2.HealthCheckResponse.ServingStatus.SERVING
        NOT_SERVING = hpb2.HealthCheckResponse.ServingStatus.NOT_SERVING

        backend = FailoverBackend(
            FaultInjectionBackend(CpuBackend(), FaultPlan().fail_after(0)),
            CpuBackend(), recovery_after_s=None,
        )
        state = ServerState()
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), port=0, backend=backend
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                # healthy boot: both views SERVING
                assert (await client.health_check()).status == SERVING
                assert (
                    await client.health_check(service="readiness")
                ).status == SERVING

                # WAL recovery in flight: readiness drops, liveness stays
                server.health.recovering = True
                assert (await client.health_check()).status == SERVING
                assert (
                    await client.health_check(service="readiness")
                ).status == NOT_SERVING
                server.health.recovering = False

                # breaker open: readiness drops, liveness stays (the
                # fallback still answers — do not restart-loop it)
                backend.breaker.record_failure()
                assert backend.degraded
                assert (await client.health_check()).status == SERVING
                assert (
                    await client.health_check(service="readiness")
                ).status == NOT_SERVING
                # the auth service name selects the readiness view too
                assert (
                    await client.health_check(service="auth.AuthService")
                ).status == NOT_SERVING

                # operator re-arm: readiness returns
                backend.reset()
                assert (
                    await client.health_check(service="readiness")
                ).status == SERVING

                # graceful drain flips BOTH views
                server.health.serving = False
                assert (await client.health_check()).status == NOT_SERVING
                assert (
                    await client.health_check(service="readiness")
                ).status == NOT_SERVING
        finally:
            await server.stop(None)

    run(main())


# --- REPL /overload ----------------------------------------------------------


def test_overload_repl_command():
    from cpzk_tpu.server.__main__ import handle_command

    async def main():
        state = ServerState()
        out, quit_ = await handle_command("/overload", state, None, None, None)
        assert "admission control disabled" in out and not quit_

        t = [0.0]
        c = _controller(lambda: (0.0, 0.0), lambda: t[0],
                        per_client_rpm=60, per_client_burst=1)
        c.admit("VerifyProof", "a")
        c.admit("VerifyProof", "a")  # second one: per-client shed
        out, quit_ = await handle_command(
            "/ov", state, None, None, c
        )
        assert not quit_
        assert "level=3.00/3" in out
        assert "admitting=verify+challenge+register" in out
        assert "clients=1/1024" in out
        assert re.search(r"shed\{client=\d+ priority=\d+ global=\d+\}", out)

    run(main())


# --- config: layering, validation, drift guard -------------------------------


def test_admission_config_layering_and_validation(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = ServerConfig.from_env()
    assert cfg.admission.enabled is True
    assert cfg.admission.per_client_rpm == 0  # 0 = disabled (unset)
    cfg.validate()  # defaults are valid

    (tmp_path / "server.toml").write_text(
        "[admission]\nper_client_rpm = 120\nmax_clients = 64\n"
        "decrease_factor = 0.25\n"
    )
    monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
    cfg = ServerConfig.from_env()
    assert cfg.admission.per_client_rpm == 120
    assert cfg.admission.max_clients == 64
    assert cfg.admission.decrease_factor == 0.25
    cfg.validate()
    # env overrides TOML
    monkeypatch.setenv("SERVER_ADMISSION_PER_CLIENT_RPM", "30")
    monkeypatch.setenv("SERVER_ADMISSION_HIGH_WATERMARK", "0.9")
    monkeypatch.setenv("SERVER_ADMISSION_ENABLED", "false")
    cfg = ServerConfig.from_env()
    assert cfg.admission.per_client_rpm == 30
    assert cfg.admission.high_watermark == 0.9
    assert cfg.admission.enabled is False

    def invalid(**kw):
        bad = ServerConfig()
        for key, value in kw.items():
            setattr(bad.admission, key, value)
        with pytest.raises(ValueError, match="admission"):
            bad.validate()

    invalid(per_client_rpm=-1)
    invalid(per_client_burst=0)
    invalid(max_clients=0)
    invalid(low_watermark=0.8, high_watermark=0.5)
    invalid(high_watermark=1.5)
    invalid(target_queue_wait_ms=-1)
    invalid(adjust_interval_ms=0)
    invalid(increase_step=0)
    invalid(decrease_factor=1.0)
    invalid(retry_after_min_ms=100, retry_after_max_ms=50)


def test_rate_limit_validation_rejects_negatives():
    """Satellite fix: negative requests_per_minute / burst used to slip
    through validation (and refill the bucket backwards)."""
    for field, value, match in (
        ("requests_per_minute", 0, "cannot be zero"),
        ("requests_per_minute", -5, "cannot be negative"),
        ("burst", 0, "cannot be zero"),
        ("burst", -1, "cannot be negative"),
    ):
        bad = ServerConfig()
        setattr(bad.rate_limit, field, value)
        with pytest.raises(ValueError, match=match):
            bad.validate()


def test_admission_config_keys_documented():
    """CI drift guard (pattern from test_durability.py): every [admission]
    knob ships in the TOML example, the .env example, and the
    operations-doc knob inventory."""
    keys = [f.name for f in dataclasses.fields(AdmissionSettings)]
    assert keys  # the guard itself must not silently go vacuous

    toml_text = (ROOT / "config" / "server.toml.example").read_text()
    m = re.search(r"^\[admission\]$", toml_text, re.M)
    assert m, "[admission] section missing from config/server.toml.example"
    section = toml_text[m.end():].split("\n[", 1)[0]
    env_text = (ROOT / ".env.example").read_text()
    docs = (ROOT / "docs" / "operations.md").read_text()
    for key in keys:
        assert re.search(rf"^{key}\s*=", section, re.M), (
            f"[admission] key {key!r} missing from config/server.toml.example"
        )
        assert f"SERVER_ADMISSION_{key.upper()}" in env_text, (
            f"SERVER_ADMISSION_{key.upper()} missing from .env.example"
        )
        assert f"`admission.{key}`" in docs, (
            f"`admission.{key}` missing from the docs/operations.md "
            "knob inventory"
        )


def test_admission_metrics_registered_and_typed():
    # touching the controller registers the admission families; the
    # process-wide inventory guard (test_metrics_inventory) then keeps
    # them documented
    t = [0.0]
    c = _controller(lambda: (0.0, 0.0), lambda: t[0],
                    per_client_rpm=60, per_client_burst=1)
    before = metrics.read("admission.admitted")
    c.admit("VerifyProof", "m1")
    c.admit("VerifyProof", "m1")
    assert metrics.read("admission.admitted") - before == 1.0
    assert metrics.read("admission.shed.per_client") >= 1.0
    assert metrics.read("admission.level", kind="g") >= MIN_LEVEL
    assert metrics.read("admission.clients", kind="g") >= 1.0
