"""Sharded ingest (server/ingest.py): N SO_REUSEPORT listener processes
feeding the dispatch/state process over the WAL's CRC-framed discipline.
Pins: frame integrity, end-to-end serving through real shard processes,
per-entry malformed-wire parity with the in-process paths, shard-death
respawn with the daemon serving throughout, and the structural guarantee
that ``ingest_shards = 1`` never even imports this machinery.
"""

import asyncio
import contextlib
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server import ingest as ingest_mod
from cpzk_tpu.server.service import serve

ROOT = pathlib.Path(__file__).resolve().parent.parent


def run(coro):
    return asyncio.run(coro)


# --- framing (the wal.iter_frames discipline over the shard seam) -----------


def test_frame_roundtrip_and_corruption():
    async def main():
        payload = b"x" * 1000
        frame = ingest_mod.pack_frame(payload)
        reader = asyncio.StreamReader()
        reader.feed_data(frame)
        reader.feed_eof()
        assert await ingest_mod.read_frame(reader) == payload
        assert await ingest_mod.read_frame(reader) is None  # clean EOF

        # CRC corruption: torn down, never surfaced as a frame
        bad = bytearray(frame)
        bad[-1] ^= 1
        reader = asyncio.StreamReader()
        reader.feed_data(bytes(bad))
        reader.feed_eof()
        with pytest.raises(ValueError, match="CRC"):
            await ingest_mod.read_frame(reader)

        # garbage length field: bounded allocation, loud failure
        reader = asyncio.StreamReader()
        reader.feed_data(b"\xff\xff\xff\xff\x00\x00\x00\x00" + b"z" * 64)
        reader.feed_eof()
        with pytest.raises(ValueError, match="out of bounds"):
            await ingest_mod.read_frame(reader)
    run(main())


def test_frame_payload_cap():
    with pytest.raises(ValueError, match="exceeds"):
        ingest_mod.pack_frame(b"x" * (ingest_mod.MAX_INGEST_FRAME + 1))


# --- end-to-end through real shard processes --------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@contextlib.asynccontextmanager
async def _sharded_stack(shards: int = 2):
    state = ServerState()
    server, _ = await serve(
        state, RateLimiter(10**9, 10**9), port=0, listen=False)
    port = _free_port()
    sup = ingest_mod.IngestSupervisor(
        server.auth_service, server.health,
        shards=shards, host="127.0.0.1", port=port,
    )
    await sup.start()
    try:
        # wait for every shard to bind + connect the dispatch seam
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(s["connected"] for s in sup.shard_stats.values()):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(f"shards never connected: {sup.status()}")
        yield sup, port, state, server
    finally:
        await sup.stop()
        await server.stop(None)


def _corpus(n=4):
    rng = SecureRng()
    params = Parameters.new()
    provers = [Prover(params, Witness(Ristretto255.random_scalar(rng)))
               for _ in range(n)]
    return rng, provers


async def _login_wave(client, provers, rng, prefix):
    eb = Ristretto255.element_to_bytes
    ids = [f"{prefix}{i}" for i in range(len(provers))]
    resp = await client.register_batch(
        ids,
        [eb(p.statement.y1) for p in provers],
        [eb(p.statement.y2) for p in provers],
    )
    assert all(r.success for r in resp.results), [
        r.message for r in resp.results]
    cids, proofs = [], []
    for uid, p in zip(ids, provers):
        ch = await client.create_challenge(uid)
        cid = bytes(ch.challenge_id)
        t = Transcript()
        t.append_context(cid)
        cids.append(cid)
        proofs.append(p.prove_with_transcript(rng, t).to_bytes())
    return ids, cids, proofs


def test_sharded_ingest_serves_batch_stream_health():
    rng, provers = _corpus()

    async def main():
        async with _sharded_stack(shards=2) as (sup, port, _state, _server):
            async with AuthClient(f"127.0.0.1:{port}") as client:
                ids, cids, proofs = await _login_wave(
                    client, provers, rng, "w")
                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert all(r.success for r in resp.results), [
                    r.message for r in resp.results]
                # stream through the proxy (reader/responder + credits)
                ids, cids, proofs = [], [], []
                for i, p in enumerate(provers):
                    ch = await client.create_challenge(f"w{i}")
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    ids.append(f"w{i}")
                    cids.append(cid)
                    proofs.append(p.prove_with_transcript(rng, t).to_bytes())
                n_ok = 0
                async for chunk in client.verify_proof_stream_chunks(
                    list(zip(ids, cids, proofs)), chunk=2
                ):
                    n_ok += sum(chunk[1])
                assert n_ok == len(provers)
                # health proxied too
                hc = await client.health_check()
                assert hc is not None
            st = sup.status()
            assert sum(s["rpcs"] for s in st["per_shard"]) > 0
            assert sum(s["parses"] for s in st["per_shard"]) > 0
    run(main())


def test_sharded_malformed_batch_parity_with_in_process():
    """Satellite 3, sharded leg: a coalesced batch with malformed wires
    answers through the shard seam byte-identically to the in-process
    native path (same handlers, same deserializers — pinned anyway)."""
    rng, provers = _corpus()

    async def in_process():
        state = ServerState()
        server, port = await serve(
            state, RateLimiter(10**9, 10**9), port=0, wire="native")
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                return await _mixed_wave(client, provers, rng)
        finally:
            await server.stop(None)

    async def sharded():
        async with _sharded_stack(shards=2) as (_sup, port, _state, _server):
            async with AuthClient(f"127.0.0.1:{port}") as client:
                return await _mixed_wave(client, provers, rng)

    async def _mixed_wave(client, provers, rng):
        ids, cids, proofs = await _login_wave(client, provers, rng, "m")
        proofs[1] = proofs[1][:50]
        proofs[2] = b""
        resp = await client.verify_proof_batch(ids, cids, proofs)
        return [(r.success, r.message) for r in resp.results]

    a = run(in_process())
    b = run(sharded())
    assert a == b
    assert a[0][0] is True and a[3][0] is True
    assert a[1] == (False, "Invalid proof: Truncated proof: incomplete r2 data")
    assert a[2] == (False, "Empty proof 2")


def test_shard_sigkill_respawn_and_serving_through_it():
    rng, provers = _corpus(2)

    async def main():
        async with _sharded_stack(shards=2) as (sup, port, _state, _server):
            async with AuthClient(f"127.0.0.1:{port}") as client:
                ids, cids, proofs = await _login_wave(
                    client, provers, rng, "k")
                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert all(r.success for r in resp.results)
            # SIGKILL shard 0: the daemon keeps serving (remaining shard
            # accepts new connections), and the supervisor respawns it
            victim = sup.shard_stats[0]["pid"]
            os.kill(victim, signal.SIGKILL)
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                row = sup.shard_stats[0]
                if row["respawns"] >= 1 and row["pid"] not in (None, victim):
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError(f"shard never respawned: {sup.status()}")
            assert sup.respawns >= 1
            # serving survived the whole time; retry absorbs the window
            # where a connection could still land on the dying listener
            for _ in range(10):
                try:
                    async with AuthClient(f"127.0.0.1:{port}") as client:
                        ids, cids, proofs = await _login_wave(
                            client, [provers[0]], rng, f"k2-{_}-")
                        resp = await client.verify_proof_batch(
                            ids, cids, proofs)
                        assert all(r.success for r in resp.results)
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            else:
                raise AssertionError("daemon stopped serving after the kill")
            # the respawned shard reconnects the dispatch seam
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if sup.shard_stats[0]["connected"]:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("respawned shard never reconnected")
    run(main())


_SINGLE_SHARD_SCRIPT = """
import asyncio, signal, sys
import cpzk_tpu.server.__main__ as daemon

args = daemon.parse_args(["--no-repl", "--port", "0"])

async def main():
    loop = asyncio.get_running_loop()
    task = loop.create_task(daemon.amain(args))
    # poll-until-deadline, not a wall-clock nap: the daemon installs its
    # signal handlers right after the listener binds, and asyncio's
    # add_signal_handler swaps SIGTERM off SIG_DFL — the observable
    # "bound and ready for a clean TERM" marker
    deadline = loop.time() + 60.0
    while loop.time() < deadline:
        if task.done():
            await task  # surface the boot failure
            raise AssertionError("daemon exited before being signalled")
        if signal.getsignal(signal.SIGTERM) is not signal.SIG_DFL:
            break
        await asyncio.sleep(0.05)
    else:
        raise AssertionError("daemon never installed its signal handlers")
    assert "cpzk_tpu.server.ingest" not in sys.modules, "ingest imported!"
    signal.raise_signal(signal.SIGTERM)
    await task

asyncio.run(main())
assert "cpzk_tpu.server.ingest" not in sys.modules
print("SINGLE-SHARD-STRUCTURAL-OK")
"""


def test_ingest_shards_1_structurally_unchanged(tmp_path):
    """The spy pin: at the default ``ingest_shards = 1`` the daemon
    binds in-process and the ingest machinery is never imported, let
    alone constructed — today's hot path, byte for byte."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("SERVER_INGEST_SHARDS", None)
    env["SERVER_CONFIG_PATH"] = str(tmp_path / "none.toml")  # no config pickup
    env["PYTHONPATH"] = str(ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    result = subprocess.run(
        [sys.executable, "-c", _SINGLE_SHARD_SCRIPT],
        capture_output=True, text=True, cwd=str(tmp_path), env=env,
        timeout=120,
    )
    assert result.returncode == 0, result.stderr
    assert "SINGLE-SHARD-STRUCTURAL-OK" in result.stdout
