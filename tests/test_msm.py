"""Differential tests: windowed-Pippenger MSM kernel vs the integer-exact
host edwards module, plus the TpuBackend dispatch into it.

The MSM is the flagship kernel (SURVEY.md §7 hard part #1) standing in for
the reference's per-row accumulation loop at ``src/verifier/batch.rs:271-312``.
"""

import secrets

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cpzk_tpu.core import edwards as he
from cpzk_tpu.core import scalars as hs
from cpzk_tpu.ops import curve, msm


def host_msm(points, scalars):
    acc = he.IDENTITY
    for p, k in zip(points, scalars):
        acc = he.pt_add(acc, he.pt_scalar_mul(p, k))
    return acc


def run_msm(points, scalars, c):
    pts = curve.points_to_device(points)
    digits = jnp.asarray(msm.scalars_to_signed_digits(scalars, c))
    out = jax.jit(msm.msm_kernel, static_argnums=2)(pts, digits, c)
    got = curve.points_from_device(jax.device_get(out))[0]
    return tuple(v % he.P for v in got)


def rand_point():
    return he.pt_scalar_mul(he.BASEPOINT, secrets.randbelow(hs.L))


# all small-m cases share one (m=16, c=6) program: pad with identity points
# and zero scalars so a single XLA compile covers every scenario
C = 6
M = 16


def padded(points, scalars):
    points = points + [he.IDENTITY] * (M - len(points))
    scalars = scalars + [0] * (M - len(scalars))
    return points, scalars


@pytest.mark.parametrize("m", [1, 5, 16])
def test_msm_matches_host(m):
    points = [rand_point() for _ in range(m)]
    scalars = [secrets.randbelow(hs.L) for _ in range(max(0, m - 3))]
    scalars += [0, 1, hs.L - 1][: m - len(scalars)]
    points, scalars = padded(points, scalars)
    assert he.pt_eq(run_msm(points, scalars, C), host_msm(points, scalars))


def test_msm_duplicate_buckets():
    """Many terms landing in the same bucket exercises the segment sums."""
    p = rand_point()
    points, scalars = padded([p] * 12, [3] * 12)  # one crowded bucket
    assert he.pt_eq(run_msm(points, scalars, C), host_msm(points, scalars))


def test_msm_identity_output():
    x = secrets.randbelow(hs.L)
    points, scalars = padded([he.BASEPOINT, he.BASEPOINT], [x, hs.L - x])
    pts = curve.points_to_device(points)
    digits = jnp.asarray(msm.scalars_to_signed_digits(scalars, C))
    ok = jax.jit(msm.msm_is_identity_kernel, static_argnums=2)(pts, digits, C)
    assert bool(ok)


@pytest.mark.parametrize("c", [5, 7])
def test_msm_matches_host_across_windows(c):
    """Window-size variation of the kernel-vs-host differential: the
    round-5 16k device anomaly (PROFILE.md §7a) made window dependence a
    first-class suspicion; c in {8, 11, 12, 13, 14, 15} was cleared on
    CPU in-round by a one-off oracle sweep (PROFILE.md §7a), and this
    pins two non-default windows in the default
    suite so a window-dependent regression (digit recode interplay,
    bucket-boundary searchsorted, Horner double count) can't land
    silently.  Small windows keep the extra XLA programs compile-cheap."""
    points = [rand_point() for _ in range(M - 2)] + [he.IDENTITY]
    scalars = [secrets.randbelow(hs.L) for _ in range(M - 3)] + [0, hs.L - 1]
    points, scalars = padded(points, scalars)
    assert he.pt_eq(run_msm(points, scalars, c), host_msm(points, scalars))


def test_signed_digit_recode_roundtrip():
    for c in (4, 7, 13, 16):
        vals = [0, 1, hs.L - 1, secrets.randbelow(hs.L), (1 << 252)]
        digits = msm.scalars_to_signed_digits(vals, c)
        assert digits.shape == (msm.num_windows(c), len(vals))
        half = 1 << (c - 1)
        assert np.abs(digits).max() <= half
        for j, v in enumerate(vals):
            rec = sum(int(digits[k, j]) << (c * k) for k in range(digits.shape[0]))
            assert rec == v


def test_pick_window_grows_with_m():
    cs = [msm.pick_window(m) for m in (256, 8192, 262144)]
    assert cs == sorted(cs)
    assert cs[0] >= 4 and cs[-1] <= 16


def test_backend_pippenger_path():
    """BatchVerifier + TpuBackend routed through the Pippenger MSM: valid
    batch accepts; a corrupted row falls back to per-proof results.  The
    single-device default never picks Pippenger (calibrated loser on
    silicon, ``backend.PIPPENGER_MIN_ROWS``), so the crossover is pinned
    low explicitly here."""
    from cpzk_tpu import BatchVerifier, Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.backend import TpuBackend

    rng = SecureRng()
    params = Parameters.new()
    n = 35
    bv = BatchVerifier(backend=TpuBackend(pippenger_min=32))
    proofs = []
    for _ in range(n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proof = prover.prove_with_transcript(rng, Transcript())
        proofs.append((prover.statement, proof))
        bv.add(params, prover.statement, proof)
    assert bv.verify(rng) == [None] * n

    # corrupt one row: statement/proof mismatch -> combined fails -> fallback
    bad = BatchVerifier(backend=TpuBackend(pippenger_min=32))
    for i, (st, pr) in enumerate(proofs):
        other = proofs[0][1] if i == n - 1 else pr
        bad.add(params, st, other if i == n - 1 else pr)
    results = bad.verify(rng)
    assert results[: n - 1] == [None] * (n - 1)
    assert results[n - 1] is not None
