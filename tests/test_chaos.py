"""Chaos suite: deterministic fault injection through the full serving
stack (resilience subsystem tentpole).

Every scenario is driven by a seeded :class:`FaultPlan`, so "the TPU dies
mid-batch", "the device flaps and stabilizes", "a deadline storm hits a
saturated queue" and "the queue sheds overload" are exact, replayable
schedules — not sampled timing windows.  The invariants under test:

- accept/reject results are ALWAYS the CPU ground truth, through every
  failover, probe, and recovery (zero wrong answers);
- a flapping-then-stable primary ends with the breaker CLOSED (traffic
  back on the TPU plane) without operator intervention;
- queue entries whose RPC deadline passed are resolved as
  DEADLINE_EXCEEDED and never reach the device;
- gRPC health stays SERVING while degraded (the fallback still answers);
- the client retry policy retries transient codes for idempotent-safe
  RPCs only, within its budget, and never resends a consumed challenge.
"""

import asyncio
import random
import threading
import time

import grpc
import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.protocol.batch import (
    BatchVerifier,
    CpuBackend,
    FailoverBackend,
    VerifierBackend,
)
from cpzk_tpu.resilience import RetryBudget, RetryPolicy
from cpzk_tpu.resilience.breaker import BreakerState, CircuitBreaker
from cpzk_tpu.resilience.faults import FaultInjectionBackend, FaultPlan
from cpzk_tpu.server import RateLimiter, ServerState, metrics
from cpzk_tpu.server.state import UserData
from cpzk_tpu.server.batching import DeadlineExceeded, DynamicBatcher, QueueFull
from cpzk_tpu.server.service import serve

pytestmark = pytest.mark.chaos


def run(coro):
    return asyncio.run(coro)


def make_proofs(n, params=None, rng=None):
    rng = rng or SecureRng()
    params = params or Parameters.new()
    out = []
    for _ in range(n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proof = prover.prove_with_transcript(rng, Transcript())
        out.append((prover.statement, proof))
    return params, out


# --- breaker state machine ---------------------------------------------------


def test_breaker_state_machine():
    t = [0.0]
    br = CircuitBreaker(recovery_after_s=10.0, clock=lambda: t[0])
    assert br.state is BreakerState.CLOSED
    assert br.acquire() == "primary"

    assert br.record_failure() is True  # caller that transitioned
    assert br.record_failure() is False  # concurrent batch: no double-count
    assert br.state is BreakerState.OPEN

    t[0] = 9.9
    assert br.acquire() == "fallback"  # cooldown not served
    t[0] = 10.0
    assert br.acquire() == "probe"  # exactly one probe granted
    assert br.state is BreakerState.HALF_OPEN
    assert br.acquire() == "fallback"  # probe already in flight

    br.probe_failed()
    assert br.state is BreakerState.OPEN
    t[0] = 15.0
    assert br.acquire() == "fallback"  # cooldown restarted at t=10
    t[0] = 20.0
    assert br.acquire() == "probe"
    br.probe_succeeded()
    assert br.state is BreakerState.CLOSED
    assert br.acquire() == "primary"
    assert br.degraded_seconds == pytest.approx(20.0)  # t=0 .. t=20

    # release_probe hands the token back without restarting the cooldown
    br.record_failure()  # t=20
    t[0] = 30.0
    assert br.acquire() == "probe"
    br.release_probe()
    assert br.acquire() == "probe"  # immediately re-grantable

    # recovery_after_s=None: the legacy permanent latch
    t2 = [0.0]
    br2 = CircuitBreaker(recovery_after_s=None, clock=lambda: t2[0])
    br2.record_failure()
    t2[0] = 1e9
    assert br2.acquire() == "fallback"
    br2.reset()
    assert br2.acquire() == "primary"


def test_breaker_probe_token_is_exclusive_across_threads():
    t = [100.0]
    br = CircuitBreaker(recovery_after_s=0.0, clock=lambda: t[0])
    br.record_failure()
    routes = []
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        routes.append(br.acquire())

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert routes.count("probe") == 1
    assert routes.count("fallback") == 7


# --- fault plan determinism --------------------------------------------------


def test_fault_plan_is_deterministic():
    def build():
        return (
            FaultPlan(seed=3)
            .fail_on(2)
            .fail_range(5, 7)
            .flap(period=3, fail=1, start=9, until=15)
        )

    expected = {2, 5, 6, 9, 12}
    assert {i for i in range(20) if build().should_fail(i)} == expected
    # identical plans -> identical schedules, run after run
    a, b = build(), build()
    assert [a.should_fail(i) for i in range(50)] == [b.should_fail(i) for i in range(50)]

    p1 = FaultPlan(seed=1).fail_probability(0.5, until=200)
    p2 = FaultPlan(seed=1).fail_probability(0.5, until=200)
    seq = [p1.should_fail(i) for i in range(200)]
    assert seq == [p2.should_fail(i) for i in range(200)]
    assert any(seq) and not all(seq)  # actually probabilistic
    # different seed -> different draw
    p3 = FaultPlan(seed=2).fail_probability(0.5, until=200)
    assert seq != [p3.should_fail(i) for i in range(200)]

    lat = FaultPlan(seed=2).latency(0.1, every=4)
    assert lat.latency_for(0) > 0 and lat.latency_for(1) == 0.0
    assert lat.latency_for(0) == FaultPlan(seed=2).latency(0.1, every=4).latency_for(0)
    assert 0.05 <= lat.latency_for(4) <= 0.15  # ±50% jitter band

    plan = FaultPlan().snapshot_errors(2)
    assert plan.take_snapshot_error() and plan.take_snapshot_error()
    assert not plan.take_snapshot_error()

    assert FaultPlan().fail_after(3).should_fail(10**9)
    with pytest.raises(ValueError):
        FaultPlan().flap(period=0, fail=0)


# --- failover self-healing ---------------------------------------------------


def test_failover_self_heals_after_transient_fault():
    """Fail once, cool down, probe, re-arm: the one-way latch is gone."""
    params, proofs = make_proofs(4)
    t = [0.0]
    fault = FaultInjectionBackend(CpuBackend(), FaultPlan().fail_on(0))
    backend = FailoverBackend(
        fault, CpuBackend(), recovery_after_s=5.0, clock=lambda: t[0]
    )
    rng = SecureRng()

    def verify_wave():
        bv = BatchVerifier(backend=backend)
        for st, pr in proofs:
            bv.add(params, st, pr)
        return bv.verify(rng)

    assert verify_wave() == [None] * 4  # batch 0: injected fault -> fallback
    assert backend.degraded and backend.state is BreakerState.OPEN
    assert fault.faults_raised == 1

    assert verify_wave() == [None] * 4  # still cooling down: primary untouched
    assert fault.batches_seen == 1

    t[0] = 5.0
    assert verify_wave() == [None] * 4  # probe batch: primary agrees
    assert backend.state is BreakerState.CLOSED and not backend.degraded
    assert fault.batches_seen == 2

    before = fault.batches_seen
    assert verify_wave() == [None] * 4  # traffic is back on the primary
    assert fault.batches_seen == before + 1


class LyingBackend(VerifierBackend):
    """A device that comes back WRONG: accepts every proof."""

    prefers_combined = False

    def verify_combined(self, rows, beta):  # pragma: no cover - unused
        raise AssertionError("unused")

    def verify_each(self, rows):
        return [1] * len(rows)


def test_probe_disagreement_keeps_fallback_authoritative():
    """A primary that answers — incorrectly — never re-arms, and its wrong
    answers are never returned to callers."""
    params, proofs = make_proofs(3)
    t = [0.0]
    lying = FaultInjectionBackend(LyingBackend(), FaultPlan().fail_on(0))
    backend = FailoverBackend(
        lying, CpuBackend(), recovery_after_s=1.0, clock=lambda: t[0]
    )
    rng = SecureRng()

    def verify_wave():
        bv = BatchVerifier(backend=backend)
        bv.add(params, proofs[0][0], proofs[0][1])
        bv.add(params, proofs[1][0], proofs[1][1])
        bv.add(params, proofs[0][0], proofs[2][1])  # mismatched -> must reject
        return bv.verify(rng)

    def assert_truth(results):
        assert results[0] is None and results[1] is None and results[2] is not None

    assert_truth(verify_wave())  # batch 0 raises -> open
    assert backend.state is BreakerState.OPEN
    for round_no in range(3):
        t[0] += 1.0
        assert_truth(verify_wave())  # probe: lying primary accepts row 2
        assert backend.state is BreakerState.OPEN, round_no  # never re-arms


def test_probe_respects_probe_batch_max():
    """The probe re-verifies at most probe_batch_max rows on the primary."""
    params, proofs = make_proofs(6)
    t = [0.0]

    class RowCounting(CpuBackend):
        seen_rows: list = []

        def verify_each(self, rows):
            self.seen_rows.append(len(rows))
            return super().verify_each(rows)

    counting = RowCounting()
    fault = FaultInjectionBackend(counting, FaultPlan().fail_on(0))
    backend = FailoverBackend(
        fault, CpuBackend(), recovery_after_s=0.0, probe_batch_max=2,
        clock=lambda: t[0],
    )
    rng = SecureRng()
    for _ in range(2):  # batch 0 trips, batch 1 probes
        bv = BatchVerifier(backend=backend)
        for st, pr in proofs:
            bv.add(params, st, pr)
        assert bv.verify(rng) == [None] * 6
    assert backend.state is BreakerState.CLOSED
    assert counting.seen_rows == [2]  # the probe slice, nothing more


# --- deadline shedding -------------------------------------------------------


class RowCountingBackend(CpuBackend):
    def __init__(self):
        self.rows_verified = 0

    def verify_each(self, rows):
        self.rows_verified += len(rows)
        return super().verify_each(rows)


def test_expired_entries_shed_before_dispatch():
    """Acceptance: expired queue entries resolve as DEADLINE_EXCEEDED and
    are never verified."""
    params, proofs = make_proofs(5)
    backend = RowCountingBackend()
    expired_before = metrics.read("tpu.queue.expired")

    async def main():
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=30.0)
        batcher.start()
        now = time.monotonic()
        coros = [
            batcher.submit(params, st, pr, None, deadline=now + 30.0)
            for st, pr in proofs[:3]
        ] + [
            batcher.submit(params, st, pr, None, deadline=now - 0.001)
            for st, pr in proofs[3:]
        ]
        results = await asyncio.gather(*coros, return_exceptions=True)
        await batcher.stop()
        return results

    results = run(main())
    assert results[:3] == [None] * 3
    assert all(isinstance(r, DeadlineExceeded) for r in results[3:])
    assert backend.rows_verified == 3  # zero device rows for expired entries
    assert metrics.read("tpu.queue.expired") - expired_before == 2


def test_shed_expired_toggle_off_verifies_everything():
    params, proofs = make_proofs(2)
    backend = RowCountingBackend()

    async def main():
        batcher = DynamicBatcher(
            backend, max_batch=64, window_ms=10.0, shed_expired=False
        )
        batcher.start()
        now = time.monotonic()
        results = await asyncio.gather(
            *[
                batcher.submit(params, st, pr, None, deadline=now - 1.0)
                for st, pr in proofs
            ]
        )
        await batcher.stop()
        return results

    assert run(main()) == [None, None]  # verified despite expiry
    assert backend.rows_verified == 2


def test_cancelled_entries_dropped_and_counted_once():
    """RPCs cancelled while queued are dropped at drain time (no device
    work) and counted into tpu.queue.abandoned exactly once."""
    params, proofs = make_proofs(4)
    backend = RowCountingBackend()
    abandoned_before = metrics.read("tpu.queue.abandoned")

    async def main():
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=50.0)
        batcher.start()
        futs = [
            asyncio.ensure_future(batcher.submit(params, st, pr, None))
            for st, pr in proofs
        ]
        await asyncio.sleep(0.01)  # everything enqueued, window still open
        futs[0].cancel()
        futs[1].cancel()
        results = await asyncio.gather(*futs, return_exceptions=True)
        await batcher.stop()
        return results

    results = run(main())
    assert all(isinstance(r, asyncio.CancelledError) for r in results[:2])
    assert results[2:] == [None, None]
    assert backend.rows_verified == 2  # only the live pair hit the device
    assert metrics.read("tpu.queue.abandoned") - abandoned_before == 2


def test_grpc_threads_rpc_deadline_into_batcher():
    """The serving layer converts the gRPC deadline into an absolute
    monotonic deadline on the queued entry."""

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        batcher = DynamicBatcher(CpuBackend(), max_batch=64, window_ms=5.0)
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), port=0, batcher=batcher
        )
        captured = []
        orig_submit = batcher.submit

        async def spy(params_, statement, proof, context, deadline=None,
                      trace_id=None):
            captured.append(deadline)
            return await orig_submit(
                params_, statement, proof, context, deadline=deadline,
                trace_id=trace_id,
            )

        batcher.submit = spy
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                cid, pf = await _register_and_prove(client, "dl-user", rng, params)
                t0 = time.monotonic()
                resp = await client.verify_proof("dl-user", cid, pf, timeout=30.0)
                assert resp.success

                cid2, pf2 = await _register_and_prove(client, "dl-user2", rng, params)
                resp = await client.verify_proof("dl-user2", cid2, pf2)  # no deadline
                assert resp.success
        finally:
            await batcher.stop()
            await server.stop(None)
        assert len(captured) == 2
        assert captured[0] is not None
        assert 0.0 < captured[0] - t0 <= 30.5  # absolute monotonic deadline
        assert captured[1] is None

    run(main())


def test_grpc_deadline_storm_never_reaches_device():
    """Client deadlines fire while entries sit in a slow queue: the drain
    drops every one of them (cancelled or expired) without device work,
    and the server stays healthy for the next caller."""

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        backend = RowCountingBackend()
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=400.0)
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), port=0, batcher=batcher
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = [f"storm{i}" for i in range(4)]
                pairs = [
                    await _register_and_prove(client, u, rng, params) for u in users
                ]
                # 50ms client deadlines vs a 400ms batch window: every RPC
                # times out client-side while queued
                resps = await asyncio.gather(
                    *[
                        client.verify_proof(u, cid, pf, timeout=0.05)
                        for u, (cid, pf) in zip(users, pairs)
                    ],
                    return_exceptions=True,
                )
                for r in resps:
                    assert isinstance(r, grpc.RpcError)
                    assert r.code() == grpc.StatusCode.DEADLINE_EXCEEDED
                await asyncio.sleep(0.6)  # let the window drain the queue
                assert backend.rows_verified == 0

                # the same server still serves a well-behaved login
                cid, pf = await _register_and_prove(client, "calm", rng, params)
                resp = await client.verify_proof("calm", cid, pf, timeout=5.0)
                assert resp.success
        finally:
            await batcher.stop()
            await server.stop(None)

    run(main())


# --- overload shedding -------------------------------------------------------


def test_grpc_overload_shed_resource_exhausted():
    """Submissions beyond the queue cap get RESOURCE_EXHAUSTED immediately;
    queued ones still verify."""

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        batcher = DynamicBatcher(
            CpuBackend(), max_batch=64, window_ms=250.0, max_queue=2
        )
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), port=0, batcher=batcher
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = [f"flood{i}" for i in range(6)]
                pairs = [
                    await _register_and_prove(client, u, rng, params) for u in users
                ]
                resps = await asyncio.gather(
                    *[
                        client.verify_proof(u, cid, pf)
                        for u, (cid, pf) in zip(users, pairs)
                    ],
                    return_exceptions=True,
                )
                ok = [r for r in resps if not isinstance(r, Exception)]
                shed = [r for r in resps if isinstance(r, grpc.RpcError)]
                assert len(ok) + len(shed) == 6
                assert ok and all(r.success for r in ok)
                assert shed, "queue cap of 2 must shed some of 6 concurrent RPCs"
                assert all(
                    r.code() == grpc.StatusCode.RESOURCE_EXHAUSTED for r in shed
                )
        finally:
            await batcher.stop()
            await server.stop(None)

    run(main())


def test_queue_depth_gauge_counts_inflight_entries():
    """Satellite fix: while a device batch is in flight the depth gauge
    reports its entries (not 0), and backpressure accounts for them."""
    params, proofs = make_proofs(4)
    release = threading.Event()
    entered = threading.Event()

    class GatedBackend(CpuBackend):
        def verify_each(self, rows):
            entered.set()
            release.wait(10.0)
            return super().verify_each(rows)

    async def main():
        batcher = DynamicBatcher(
            GatedBackend(), max_batch=4, window_ms=1.0, max_queue=4
        )
        batcher.start()
        coros = [
            asyncio.ensure_future(batcher.submit(params, st, pr, None))
            for st, pr in proofs
        ]
        await asyncio.to_thread(entered.wait, 10.0)
        # the queue itself is drained, but 4 entries are claimed in flight
        assert len(batcher._queue) == 0
        depth_during = metrics.read("tpu.queue.depth", kind="g")
        # in-flight entries count into backpressure too
        with pytest.raises(QueueFull):
            await batcher.submit(params, proofs[0][0], proofs[0][1], None)
        release.set()
        results = await asyncio.gather(*coros)
        await batcher.stop()
        return depth_during, results

    depth_during, results = run(main())
    assert depth_during == 4.0
    assert results == [None] * 4
    assert metrics.read("tpu.queue.depth", kind="g") == 0.0


# --- health + degradation observability --------------------------------------


def test_health_stays_serving_while_degraded():
    """Satellite: an open breaker must NOT flip gRPC health — the fallback
    still answers — but state and degraded-seconds gauges must tell on it."""

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        fault = FaultInjectionBackend(CpuBackend(), FaultPlan().fail_after(0))
        backend = FailoverBackend(fault, CpuBackend(), recovery_after_s=None)
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=5.0)
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), port=0,
            backend=backend, batcher=batcher,
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                # >= 2 concurrent proofs: single-entry batches bypass the
                # backend (BatchVerifier short-circuits n == 1 inline)
                async def wave(tag):
                    users = [f"{tag}{i}" for i in range(2)]
                    pairs = [
                        await _register_and_prove(client, u, rng, params)
                        for u in users
                    ]
                    resps = await asyncio.gather(
                        *[
                            client.verify_proof(u, cid, pf)
                            for u, (cid, pf) in zip(users, pairs)
                        ]
                    )
                    assert all(r.success for r in resps)  # fallback answered

                await wave("degraded")
                assert backend.degraded

                from cpzk_tpu.server.proto import load_health_pb2

                hpb2 = load_health_pb2()
                health = await client.health_check()
                assert health.status == hpb2.HealthCheckResponse.ServingStatus.SERVING

                await asyncio.sleep(0.05)
                await wave("still-degraded")
        finally:
            await batcher.stop()
            await server.stop(None)

    run(main())
    assert metrics.read("tpu.backend.state", kind="g") == 1.0  # open
    assert metrics.read("tpu.backend.degraded_seconds", kind="g") >= 0.05


def test_status_repl_reports_breaker_state():
    from cpzk_tpu.server.__main__ import handle_command

    fault = FaultInjectionBackend(CpuBackend(), FaultPlan().fail_after(0))
    backend = FailoverBackend(fault, CpuBackend(), recovery_after_s=None)

    async def main():
        state = ServerState()
        out, _ = await handle_command("/status", state, backend)
        assert "backend=closed" in out

        params, proofs = make_proofs(2)
        bv = BatchVerifier(backend=backend)
        for st, pr in proofs:
            bv.add(params, st, pr)
        await asyncio.to_thread(bv.verify, SecureRng())
        out, _ = await handle_command("/status", state, backend)
        assert "backend=open" in out and "degraded_for=" in out

        out, _ = await handle_command("/reset", state, backend)
        assert "re-armed" in out
        assert backend.state is BreakerState.CLOSED

        # inline CPU path: no backend to report
        out, _ = await handle_command("/status", state, None)
        assert "backend=" not in out

    run(main())


# --- client retries ----------------------------------------------------------


def test_retry_policy_backoff_and_budget():
    rng = random.Random(0)
    pol = RetryPolicy(
        max_attempts=4,
        initial_backoff_s=0.1,
        max_backoff_s=0.5,
        multiplier=2.0,
        budget=RetryBudget(tokens=2.0, token_ratio=0.5),
    )
    for attempt, cap in ((1, 0.1), (2, 0.2), (3, 0.4), (4, 0.5), (10, 0.5)):
        for _ in range(20):
            assert 0.0 <= pol.backoff_s(attempt, rng) <= cap

    assert not pol.should_retry("PERMISSION_DENIED", 1)  # non-transient
    assert not pol.should_retry("UNAVAILABLE", 4)  # attempts exhausted
    assert pol.should_retry("UNAVAILABLE", 1)  # budget 2 -> 1
    assert pol.should_retry("RESOURCE_EXHAUSTED", 2)  # budget 1 -> 0
    assert not pol.should_retry("UNAVAILABLE", 1)  # budget exhausted
    pol.note_success()
    pol.note_success()
    assert pol.should_retry("UNAVAILABLE", 1)  # refilled 2 * 0.5

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(multiplier=0.5)
    with pytest.raises(ValueError):
        RetryBudget(tokens=0)


class FakeRpcError(grpc.RpcError):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 4)
    kw.setdefault("initial_backoff_s", 0.001)
    kw.setdefault("max_backoff_s", 0.002)
    return RetryPolicy(**kw)


def test_client_retries_transient_codes_only_for_safe_rpcs():
    async def main():
        state = ServerState()
        server, port = await serve(state, RateLimiter(10_000, 10_000), port=0)
        try:
            client = AuthClient(
                f"127.0.0.1:{port}",
                retry=_fast_policy(),
                retry_rng=random.Random(7),
            )
            async with client:
                rng = SecureRng()
                params = Parameters.new()

                # CreateChallenge: idempotent-safe, retried through UNAVAILABLE
                await _register_only(client, "retry-user", rng, params)
                attempts = {"n": 0}
                real = client._stubs["CreateChallenge"]

                async def flaky(request, timeout=None, metadata=None):
                    attempts["n"] += 1
                    if attempts["n"] <= 2:
                        raise FakeRpcError(grpc.StatusCode.UNAVAILABLE)
                    return await real(request, timeout=timeout, metadata=metadata)

                client._stubs["CreateChallenge"] = flaky
                resp = await client.create_challenge("retry-user")
                assert resp.challenge_id and attempts["n"] == 3

                # non-transient codes are not retried even on safe RPCs
                attempts["n"] = 10  # stub now always delegates
                denied = {"n": 0}

                async def denied_stub(request, timeout=None, metadata=None):
                    denied["n"] += 1
                    raise FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT)

                client._stubs["Register"] = denied_stub
                with pytest.raises(grpc.RpcError):
                    await client.register("x", b"a", b"b")
                assert denied["n"] == 1

                # VerifyProof: NEVER retried (challenge consumed on first
                # receipt server-side; a resend cannot succeed)
                vattempts = {"n": 0}

                async def flaky_verify(request, timeout=None, metadata=None):
                    vattempts["n"] += 1
                    raise FakeRpcError(grpc.StatusCode.UNAVAILABLE)

                client._stubs["VerifyProof"] = flaky_verify
                with pytest.raises(grpc.RpcError):
                    await client.verify_proof("retry-user", b"c" * 32, b"p" * 8)
                assert vattempts["n"] == 1

                # budget exhaustion fails fast instead of retry-storming
                budget_client_attempts = {"n": 0}

                async def always_down(request, timeout=None, metadata=None):
                    budget_client_attempts["n"] += 1
                    raise FakeRpcError(grpc.StatusCode.UNAVAILABLE)

                client.retry = _fast_policy(
                    max_attempts=10, budget=RetryBudget(tokens=2.0, token_ratio=0.0)
                )
                client._stubs["CreateChallenge"] = always_down
                with pytest.raises(grpc.RpcError):
                    await client.create_challenge("retry-user")
                assert budget_client_attempts["n"] == 3  # initial + 2 budgeted
        finally:
            await server.stop(None)

    run(main())


def test_client_without_policy_never_retries():
    async def main():
        state = ServerState()
        server, port = await serve(state, RateLimiter(10_000, 10_000), port=0)
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                attempts = {"n": 0}

                async def down(request, timeout=None, metadata=None):
                    attempts["n"] += 1
                    raise FakeRpcError(grpc.StatusCode.UNAVAILABLE)

                client._stubs["CreateChallenge"] = down
                with pytest.raises(grpc.RpcError):
                    await client.create_challenge("nobody")
                assert attempts["n"] == 1
        finally:
            await server.stop(None)

    run(main())


# --- overload storm: fair admission + priority shedding ----------------------


def test_overload_storm_fairness_and_priority_ordering():
    """Acceptance (admission subsystem): one hot client floods a server
    whose backend is slowed by fault injection while well-behaved clients
    run normal login flows.  The hot client is throttled FIRST (its own
    keyed bucket, not the shared one), well-behaved goodput stays at 100%
    of fair share (>= the 50% floor), every shed carries retry pushback,
    and under forced overload the priority ordering holds: registrations
    and challenges shed while VerifyProof still authenticates."""
    from cpzk_tpu.admission import AdmissionController, RETRY_PUSHBACK_KEY
    from cpzk_tpu.server.config import AdmissionSettings

    plan = FaultPlan(seed=11).latency(0.02, every=1)  # every batch slowed
    backend = FaultInjectionBackend(CpuBackend(), plan)
    settings = AdmissionSettings(
        per_client_rpm=60, per_client_burst=5,  # ~5-6 admits per burst
        adjust_interval_ms=20.0,
        increase_step=1.0, decrease_factor=0.5,
    )

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        batcher = DynamicBatcher(backend, max_batch=8, window_ms=5.0)
        controller = AdmissionController(settings, batcher=batcher)
        server, port = await serve(
            state, RateLimiter(1_000_000, 1_000_000), port=0,
            backend=backend, batcher=batcher, admission=controller,
        )
        try:
            # --- phase 1: the storm.  4 well-behaved clients each run a
            # full login flow (3 RPCs, under their burst) while one hot
            # client fires 60 concurrent RPCs (~12x its fair burst).
            good = [
                AuthClient(f"127.0.0.1:{port}", client_id=f"good-{i}")
                for i in range(4)
            ]
            hot = AuthClient(f"127.0.0.1:{port}", client_id="hot")

            async def good_flow(i, client):
                cid, pf = await _register_and_prove(
                    client, f"fair-user{i}", rng, params
                )
                return await client.verify_proof(f"fair-user{i}", cid, pf)

            async def hot_call():
                try:
                    await hot.create_challenge("no-such-user")
                    return "admitted"
                except grpc.RpcError as e:
                    if e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED:
                        trailing = {
                            str(k).lower(): v
                            for k, v in (e.trailing_metadata() or ())
                        }
                        assert RETRY_PUSHBACK_KEY in trailing, (
                            "shed without retry pushback"
                        )
                        assert float(trailing[RETRY_PUSHBACK_KEY]) >= 0
                        return "shed"
                    assert e.code() == grpc.StatusCode.NOT_FOUND
                    return "admitted"

            results = await asyncio.gather(
                *[good_flow(i, c) for i, c in enumerate(good)],
                *[hot_call() for _ in range(60)],
            )
            good_resps, hot_outcomes = results[:4], results[4:]

            # well-behaved goodput: 100% of fair share (>= the 50% floor)
            assert all(r.success for r in good_resps)
            # the hot client was throttled, and throttled FIRST: its own
            # bucket shed it while every well-behaved RPC was admitted
            shed = hot_outcomes.count("shed")
            assert shed >= 40, hot_outcomes
            assert hot_outcomes.count("admitted") <= 20
            assert metrics.read("admission.shed.per_client") >= shed
            assert controller.level == pytest.approx(3.0)  # storm never
            # pushed the queue into overload: priority tier untouched

            # --- phase 2: priority ordering under forced overload.  A
            # pre-minted challenge must still verify while registrations
            # and challenge-creation shed.  Each assertion uses a FRESH
            # client id so the per-client buckets stay out of the way —
            # what's under test here is the adaptive tier alone.
            async with AuthClient(
                f"127.0.0.1:{port}", client_id="probe-setup"
            ) as setup:
                cid, pf = await _register_and_prove(
                    setup, "probe-user", rng, params
                )
            controller._signals = lambda: (0.95, 0.5)  # saturate
            async with AuthClient(
                f"127.0.0.1:{port}", client_id="probe-driver"
            ) as driver:
                deadline = time.monotonic() + 5.0
                while (
                    controller.level > 1.0 and time.monotonic() < deadline
                ):
                    await asyncio.sleep(0.025)
                    try:  # any RPC drives an AIMD adjustment
                        await driver.create_challenge("probe-user")
                    except grpc.RpcError:
                        pass
            assert controller.level == 1.0  # maximum shed

            async with AuthClient(
                f"127.0.0.1:{port}", client_id="probe-check"
            ) as probe:
                with pytest.raises(grpc.RpcError) as ei:
                    await probe.register(
                        *(await _statement_wire("probe-y", rng, params))
                    )
                assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                assert "register" in ei.value.details()
                with pytest.raises(grpc.RpcError) as ei:
                    await probe.create_challenge("probe-user")
                assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                assert "challenge" in ei.value.details()
                # ... but the in-flight login still completes: VerifyProof
                # is never rejected while lower tiers are being shed
                resp = await probe.verify_proof("probe-user", cid, pf)
                assert resp.success and resp.session_token
                assert metrics.read("admission.shed.priority") >= 2.0

                # --- phase 3: recovery.  Healthy signals re-admit tiers
                # bottom-up (additive increase), register last.
                controller._signals = lambda: (0.0, 0.0)
                deadline = time.monotonic() + 5.0
                while (
                    controller.level < 3.0 and time.monotonic() < deadline
                ):
                    await asyncio.sleep(0.025)
                    try:
                        await probe.verify_proof("probe-user", cid, pf)
                    except grpc.RpcError:
                        pass
                assert controller.level == pytest.approx(3.0)
                async with AuthClient(
                    f"127.0.0.1:{port}", client_id="probe-final"
                ) as fresh:
                    resp = await fresh.register(
                        *(await _statement_wire("probe-z", rng, params))
                    )
                    assert resp.success
        finally:
            for c in good:
                await c.close()
            await hot.close()
            await batcher.stop()
            await server.stop(None)

    run(main())


async def _statement_wire(user, rng, params):
    """(user_id, y1_wire, y2_wire) for a fresh keypair."""
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    st = prover.statement
    return (
        user,
        Ristretto255.element_to_bytes(st.y1),
        Ristretto255.element_to_bytes(st.y2),
    )


# --- the full acceptance scenario --------------------------------------------


async def _register_only(client, user, rng, params):
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    st = prover.statement
    resp = await client.register(
        user,
        Ristretto255.element_to_bytes(st.y1),
        Ristretto255.element_to_bytes(st.y2),
    )
    assert resp.success
    return prover


async def _register_and_prove(client, user, rng, params, tamper=False):
    prover = await _register_only(client, user, rng, params)
    ch = await client.create_challenge(user)
    t = Transcript()
    if tamper:
        # bind the proof to the WRONG context: parses fine, fails verify —
        # ground truth must reject it on every backend, every state
        t.append_context(b"\x00" * 32)
    else:
        t.append_context(bytes(ch.challenge_id))
    proof = prover.prove_with_transcript(rng, t)
    return bytes(ch.challenge_id), proof.to_bytes()


def test_chaos_device_loss_flap_recover_full_stack():
    """Acceptance criterion: a TPU that fails mid-batch, flaps, then
    stabilizes ends with the breaker CLOSED (back on TPU), zero wrong
    accept/reject results versus CPU ground truth, and nothing wrongly
    shed along the way."""
    # primary-exercised batches: 0 fail -> OPEN; 1 probe-fail -> OPEN;
    # 2 probe-ok -> CLOSED; 3 fail -> OPEN; 4 probe-ok -> CLOSED; 5+ stable
    plan = FaultPlan(seed=5).fail_on(0, 1, 3)
    fault = FaultInjectionBackend(CpuBackend(), plan)
    backend = FailoverBackend(
        fault, CpuBackend(), recovery_after_s=0.05, probe_batch_max=8
    )
    expired_before = metrics.read("tpu.queue.expired")

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=15.0)
        server, port = await serve(
            state, RateLimiter(100_000, 100_000), port=0,
            backend=backend, batcher=batcher,
        )
        states_seen = set()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                for wave in range(12):
                    users = [f"w{wave}u{i}" for i in range(3)]
                    pairs = [
                        await _register_and_prove(
                            client, u, rng, params, tamper=(i == 2)
                        )
                        for i, u in enumerate(users)
                    ]
                    resps = await asyncio.gather(
                        *[
                            client.verify_proof(u, cid, pf)
                            for u, (cid, pf) in zip(users, pairs)
                        ],
                        return_exceptions=True,
                    )
                    # zero wrong results, regardless of breaker state:
                    # good proofs authenticate, the tampered one never does
                    for i, r in enumerate(resps):
                        if i == 2:
                            assert isinstance(r, grpc.RpcError), (wave, i)
                            assert r.code() == grpc.StatusCode.PERMISSION_DENIED
                        else:
                            assert not isinstance(r, Exception), (wave, i, r)
                            assert r.success and r.session_token
                    states_seen.add(backend.state)
                    if (
                        wave >= 5
                        and backend.state is BreakerState.CLOSED
                        and fault.batches_seen >= 5
                    ):
                        break
                    await asyncio.sleep(0.08)  # serve the breaker cooldown

                # stabilized: breaker closed, traffic back on the primary
                assert backend.state is BreakerState.CLOSED
                assert not backend.degraded
                before = fault.batches_seen
                users = ["finalwave0", "finalwave1"]  # n >= 2: hits the backend
                pairs = [
                    await _register_and_prove(client, u, rng, params)
                    for u in users
                ]
                resps = await asyncio.gather(
                    *[
                        client.verify_proof(u, cid, pf)
                        for u, (cid, pf) in zip(users, pairs)
                    ]
                )
                assert all(r.success for r in resps)
                assert fault.batches_seen > before  # the TPU plane served it
        finally:
            await batcher.stop()
            await server.stop(None)
        return states_seen

    states_seen = run(main())
    assert fault.faults_raised == 3  # the full injected schedule ran
    assert BreakerState.OPEN in states_seen  # it really did degrade
    # nothing was wrongly shed as expired during the chaos
    assert metrics.read("tpu.queue.expired") == expired_before


def test_latency_spikes_do_not_trip_the_breaker():
    """Slow-but-correct batches are not failures: latency spikes ride
    through the pipeline without opening the breaker."""
    params, proofs = make_proofs(3)
    fault = FaultInjectionBackend(
        CpuBackend(), FaultPlan(seed=9).latency(0.03, every=2)
    )
    backend = FailoverBackend(fault, CpuBackend(), recovery_after_s=0.05)

    async def main():
        batcher = DynamicBatcher(backend, max_batch=2, window_ms=2.0)
        batcher.start()
        results = await asyncio.gather(
            *[batcher.submit(params, st, pr, None) for st, pr in proofs]
        )
        await batcher.stop()
        return results

    assert run(main()) == [None] * 3
    assert backend.state is BreakerState.CLOSED
    assert fault.batches_seen >= 1 and fault.faults_raised == 0


# --- replication failover: kill-primary -> promote -> login ------------------
#
# ISSUE 8 acceptance: SIGKILL the primary under live gRPC traffic with
# fsync=always + sync replication — the standby promotes within the lease
# window, a previously registered user completes a full challenge→verify
# login against the promoted node, no acknowledged write is lost, and the
# deposed primary's ShipSegment is fenced by epoch.


async def _make_repl_pair(tmp_path, lease_ms=400.0, renew_ms=40.0,
                          mode="sync", primary_faults=None):
    """(primary side, standby side), both serving real gRPC."""
    from cpzk_tpu.durability import DurabilityManager
    from cpzk_tpu.replication import SegmentShipper, StandbyReplica
    from cpzk_tpu.server.config import DurabilitySettings, ReplicationSettings

    sstate = ServerState()
    smgr = DurabilityManager(
        sstate, DurabilitySettings(enabled=True, fsync="always"),
        str(tmp_path / "standby.json"),
    )
    await smgr.recover()
    replica = StandbyReplica(
        sstate, smgr,
        ReplicationSettings(
            enabled=True, role="standby", lease_ms=lease_ms,
            renew_interval_ms=renew_ms, mode=mode,
        ),
    )
    sserver, sport = await serve(
        sstate, RateLimiter(100_000, 100_000), port=0, replica=replica
    )
    replica.start()

    pstate = ServerState()
    pmgr = DurabilityManager(
        pstate, DurabilitySettings(enabled=True, fsync="always"),
        str(tmp_path / "primary.json"),
    )
    await pmgr.recover()
    psettings = ReplicationSettings(
        enabled=True, role="primary", peer=f"127.0.0.1:{sport}",
        lease_ms=lease_ms, renew_interval_ms=renew_ms, mode=mode,
    )
    shipper = SegmentShipper(pstate, pmgr, psettings, faults=primary_faults)
    pmgr.attach_shipper(shipper)
    if mode == "sync":
        pstate.attach_replication_barrier(shipper.wait_replicated)
    pserver, pport = await serve(
        pstate, RateLimiter(100_000, 100_000), port=0
    )
    shipper.start()
    return (
        (pstate, pmgr, shipper, pserver, pport),
        (sstate, smgr, replica, sserver, sport),
    )


async def _await_role(replica, role, timeout=5.0):
    deadline = time.monotonic() + timeout
    while replica.role != role:
        assert time.monotonic() < deadline, (
            f"standby never became {role} (still {replica.role})"
        )
        await asyncio.sleep(0.02)


def test_kill_primary_promote_login_zero_acknowledged_loss(tmp_path):
    """THE failover acceptance scenario, end to end over real gRPC."""
    from cpzk_tpu.client.__main__ import do_login, do_register
    from cpzk_tpu.replication import SegmentShipper
    from cpzk_tpu.server.config import ReplicationSettings

    async def main():
        (pside, sside) = await _make_repl_pair(tmp_path, lease_ms=400,
                                               renew_ms=40, mode="sync")
        pstate, pmgr, shipper, pserver, pport = pside
        sstate, smgr, replica, sserver, sport = sside
        lease_t0 = None
        try:
            async with AuthClient(f"127.0.0.1:{pport}") as c:
                # live traffic against the primary: registration + a full
                # login (session + journaled challenge lifecycle)
                assert "Registered" in await do_register(c, "alice", "pw-a")
                out = await do_login(c, "alice", "pw-a")
                assert "Login OK" in out
                pre_crash_token = out.split("session: ")[1].strip()
                assert "Registered" in await do_register(c, "bob", "pw-b")
            # every acknowledged write is standby-applied (sync mode)
            assert replica.applied_seq == pmgr.wal.seq

            # the standby refuses auth traffic before promotion, and its
            # readiness view says so (liveness stays SERVING)
            from cpzk_tpu.server.proto import load_health_pb2

            hst = load_health_pb2().HealthCheckResponse.ServingStatus
            async with AuthClient(f"127.0.0.1:{sport}") as c:
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await c.create_challenge("alice")
                assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
                assert (
                    await c.health_check(service="readiness")
                ).status == hst.NOT_SERVING
                assert (await c.health_check()).status == hst.SERVING

            # SIGKILL stand-in: shipper dies mid-air, listener vanishes
            lease_t0 = time.monotonic()
            await shipper.kill()
            await pserver.stop(None)

            # the standby promotes itself within the lease window
            await _await_role(replica, "primary")
            took = time.monotonic() - lease_t0
            assert took < 5.0, f"promotion took {took:.1f}s"
            assert replica.epoch == 2

            # ... and serves a FULL login for a pre-crash user: fresh
            # challenge, proof bound to it, verify, session minted
            async with AuthClient(f"127.0.0.1:{sport}") as c:
                assert (
                    await c.health_check(service="readiness")
                ).status == hst.SERVING
                assert "Login OK" in await do_login(c, "alice", "pw-a")
                assert "Login OK" in await do_login(c, "bob", "pw-b")
                assert "Login OK" not in await do_login(c, "alice", "wrong")
            # no acknowledged write lost: the pre-crash session survives
            assert await sstate.validate_session(pre_crash_token) == "alice"

            # the deposed primary's ShipSegment is fenced by epoch
            deposed = SegmentShipper(
                pstate, pmgr,
                ReplicationSettings(
                    enabled=True, role="primary",
                    peer=f"127.0.0.1:{sport}",
                    lease_ms=400, renew_interval_ms=40,
                ),
            )
            pstate.attach_replication_barrier(None)
            await pstate.register_user(UserData("fork", _stmt(), 1))
            fenced_before = replica.applier.fenced
            deposed.start()
            deadline = time.monotonic() + 5
            while not deposed.fenced and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert deposed.fenced
            assert replica.applier.fenced > fenced_before
            assert await sstate.get_user("fork") is None  # never applied
            await deposed.kill()
        finally:
            await shipper.kill()
            await replica.stop()
            await sserver.stop(None)

    run(main())


def _stmt():
    rng = SecureRng()
    return Prover(
        Parameters.new(), Witness(Ristretto255.random_scalar(rng))
    ).statement


@pytest.mark.parametrize("point,occurrence,expect_applied", [
    # primary dies before anything ships: standby promotes clean + empty
    ("pre_ship", 0, 0),
    # primary dies mid-transfer of its SECOND segment: the torn blob is
    # refused whole, the previously-applied prefix survives promotion
    ("mid_segment", 1, 1),
])
def test_promotion_after_ship_crash_points(tmp_path, point, occurrence,
                                           expect_applied):
    from cpzk_tpu.resilience.faults import FaultPlan as _FaultPlan

    async def main():
        plan = _FaultPlan().crash_on(point, occurrence=occurrence)
        # async mode: the sync barrier would (correctly) refuse to ack the
        # write the crash point strands — here we pin standby behavior
        (pside, sside) = await _make_repl_pair(
            tmp_path, lease_ms=300, renew_ms=30, mode="async",
            primary_faults=plan,
        )
        pstate, pmgr, shipper, pserver, pport = pside
        sstate, smgr, replica, sserver, sport = sside
        try:
            # let an empty-log renewal arm the standby's lease first, so
            # the scheduled ship-crash cannot strand an unarmed standby
            deadline = time.monotonic() + 5
            while replica.lease_remaining_s is None:
                assert time.monotonic() < deadline
                await asyncio.sleep(0.01)
            await pstate.register_user(UserData("u0", _stmt(), 1))
            if occurrence > 0:
                # let the first segment land before arming the second
                deadline = time.monotonic() + 5
                while replica.applied_seq < 1 and time.monotonic() < deadline:
                    await asyncio.sleep(0.01)
                assert replica.applied_seq == 1
                await pstate.register_user(UserData("u1", _stmt(), 1))
            # the crash point fires inside the shipping loop and kills it
            deadline = time.monotonic() + 5
            while shipper.crashed is None and time.monotonic() < deadline:
                await asyncio.sleep(0.01)
            assert shipper.crashed is not None
            await pserver.stop(None)

            await _await_role(replica, "primary")
            assert replica.applied_seq == expect_applied
            if point == "mid_segment":
                # the torn segment was rejected WHOLE: prefix intact,
                # nothing half-applied
                assert replica.applier.segments_rejected >= 1
                assert await sstate.get_user("u0") is not None
                assert await sstate.get_user("u1") is None
            else:
                assert await sstate.user_count() == 0
        finally:
            await shipper.kill()
            await replica.stop()
            await sserver.stop(None)

    run(main())


_REPL_KILL_CHILD = """
import asyncio, sys
sys.path.insert(0, {root!r})

from cpzk_tpu.client.kdf import password_to_scalar
from cpzk_tpu import Parameters, Prover, Witness
from cpzk_tpu.durability import DurabilityManager
from cpzk_tpu.replication import SegmentShipper
from cpzk_tpu.server.config import DurabilitySettings, ReplicationSettings
from cpzk_tpu.server.state import ServerState, UserData

async def main():
    port = int(sys.argv[1])
    state = ServerState()
    mgr = DurabilityManager(
        state, DurabilitySettings(enabled=True, fsync="always"),
        {state_file!r},
    )
    await mgr.recover()
    settings = ReplicationSettings(
        enabled=True, role="primary", peer="127.0.0.1:%d" % port,
        lease_ms=800, renew_interval_ms=40, mode="sync",
    )
    shipper = SegmentShipper(state, mgr, settings)
    mgr.attach_shipper(shipper)
    state.attach_replication_barrier(shipper.wait_replicated)
    shipper.start()
    params = Parameters.new()
    i = 0
    while True:
        uid = "user-%04d" % i
        st = Prover(
            params, Witness(password_to_scalar("pw-" + uid, uid))
        ).statement
        await state.register_user(UserData(uid, st, 1))
        # returned: locally fsynced AND standby-applied (sync mode)
        print("ACK " + uid, flush=True)
        i += 1

asyncio.run(main())
"""


@pytest.mark.slow
def test_sigkill_primary_two_process_failover_zero_loss(tmp_path):
    """The real thing: the primary is a separate OS process registering
    users over sync replication; SIGKILL it mid-traffic.  The in-parent
    standby promotes on lease expiry, holds every acknowledged write,
    and serves a full challenge→verify login for a pre-kill user."""
    import os
    import pathlib
    import signal as _signal
    import sys as _sys

    from cpzk_tpu.client.__main__ import do_login

    root = str(pathlib.Path(__file__).resolve().parent.parent)
    script = _REPL_KILL_CHILD.format(
        root=root, state_file=str(tmp_path / "primary.json")
    )

    async def main():
        from cpzk_tpu.durability import DurabilityManager
        from cpzk_tpu.replication import StandbyReplica
        from cpzk_tpu.server.config import (
            DurabilitySettings,
            ReplicationSettings,
        )

        sstate = ServerState()
        smgr = DurabilityManager(
            sstate, DurabilitySettings(enabled=True, fsync="always"),
            str(tmp_path / "standby.json"),
        )
        await smgr.recover()
        replica = StandbyReplica(
            sstate, smgr,
            ReplicationSettings(
                enabled=True, role="standby",
                lease_ms=800, renew_interval_ms=40,
            ),
        )
        sserver, sport = await serve(
            sstate, RateLimiter(100_000, 100_000), port=0, replica=replica
        )
        replica.start()

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = await asyncio.create_subprocess_exec(
            _sys.executable, "-u", "-c", script, str(sport),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env=env, cwd=root,
        )
        acked = []
        try:
            while len(acked) < 6:
                line = await asyncio.wait_for(
                    proc.stdout.readline(), timeout=120
                )
                assert line, (await proc.stderr.read()).decode()
                if line.startswith(b"ACK "):
                    acked.append(line.split()[1].decode())
            # kill without any grace, mid-traffic (likely mid-segment)
            proc.send_signal(_signal.SIGKILL)
            await proc.wait()

            await _await_role(replica, "primary", timeout=15.0)
            # zero acknowledged-write loss: sync mode means every ACK was
            # standby-applied before the child printed it
            for uid in acked:
                assert await sstate.get_user(uid) is not None, (
                    f"acknowledged write {uid} lost across failover"
                )
            # and the promoted node completes a full login for one
            async with AuthClient(f"127.0.0.1:{sport}") as c:
                uid = acked[len(acked) // 2]
                assert "Login OK" in await do_login(c, uid, "pw-" + uid)
        finally:
            if proc.returncode is None:
                proc.kill()
                await proc.wait()
            await replica.stop()
            await sserver.stop(None)

    run(main())
