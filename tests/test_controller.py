"""Fleet controller: decision logic as pure units + fast storm legs.

The decision tests drive :meth:`FleetController.decide` directly with
hand-built :class:`Signals` snapshots and a fake clock — no daemon, no
planes — pinning the hysteresis/cooldown matrices, the dry-run parity
contract (identical decision stream, no actuator call), and the
structural safety rails (never split during promotion or over an
unfinished manifest, one action in flight).  The storm legs here are the
FAST versions of the scenarios ``benches/bench_soak.py --storm`` runs at
full scale: live split under concurrent traffic with zero acked-write
loss, lane brownout drain/re-admit, client herd damping, and the ingest
crash-loop guard.
"""

import asyncio
import os
import random

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.fleet import FleetRouter, PartitionMap
from cpzk_tpu.fleet.controller import (
    ACTION_ADMISSION_RESTORE,
    ACTION_ADMISSION_SHRINK,
    ACTION_LANE_DRAIN,
    ACTION_LANE_READMIT,
    ACTION_SPLIT,
    DECISION_EVENT,
    FleetController,
    Signals,
    run_live_split,
)
from cpzk_tpu.fleet.split import SplitError, manifest_path
from cpzk_tpu.observability import get_tracer
from cpzk_tpu.server import metrics
from cpzk_tpu.server.config import ControllerSettings, ServerConfig
from cpzk_tpu.server.state import ServerState, UserData

rng = SecureRng()
params = Parameters.new()


def run(coro):
    return asyncio.run(coro)


def make_statement():
    return Prover(params, Witness(Ristretto255.random_scalar(rng))).statement


@pytest.fixture(autouse=True)
def _fresh_tracer():
    tracer = get_tracer()
    tracer.clear()
    yield
    tracer.clear()


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_controller(clock=None, **overrides) -> FleetController:
    """A planeless controller for decide()-level tests: signals are
    injected, actuators never run (decide does not act)."""
    defaults = dict(
        enabled=True, dry_run=True, act_ticks=3, clear_ticks=2,
        split_user_threshold=100, split_lock_wait_ms=50.0,
        split_target_address="127.0.0.1:9", split_cooldown_s=600.0,
        lane_open_after_s=10.0, lane_cooldown_s=30.0,
        admission_cooldown_s=15.0,
    )
    defaults.update(overrides)
    settings = ControllerSettings(**defaults)
    return FleetController(settings, clock=clock or FakeClock(), wall=lambda: 0.0)


def lane(label, breaker="closed", drained=False, pending=0):
    return {"lane": label, "breaker": breaker, "drained": drained,
            "pending": pending}


# --- split hysteresis + cooldown ---------------------------------------------


class TestSplitDecision:
    def test_needs_act_ticks_consecutive_hot_ticks(self):
        c = make_controller()
        hot = Signals(users=150, lock_wait_ms=0.0)
        assert c.decide(hot) == []
        assert c.decide(hot) == []
        out = c.decide(hot)
        assert [d.action for d in out] == [ACTION_SPLIT]
        assert out[0].veto is None
        assert "users 150 >= 100" in out[0].reason

    def test_cold_tick_resets_the_hot_streak(self):
        c = make_controller()
        hot = Signals(users=150)
        cold = Signals(users=10)
        c.decide(hot); c.decide(hot)
        assert c.decide(cold) == []           # streak broken
        c.decide(hot); c.decide(hot)
        assert c.decide(hot)[0].action == ACTION_SPLIT

    def test_lock_wait_trigger(self):
        c = make_controller(split_user_threshold=0)
        hot = Signals(users=5, lock_wait_ms=80.0)
        c.decide(hot); c.decide(hot)
        out = c.decide(hot)
        assert out[0].action == ACTION_SPLIT
        assert "lock_wait 80.0ms >= 50.0ms" in out[0].reason

    def test_cooldown_blocks_the_next_eligible_split(self):
        clock = FakeClock()
        c = make_controller(clock, act_ticks=1)
        assert c.decide(Signals(users=150))[0].veto is None
        out = c.decide(Signals(users=150))
        assert out[0].veto == "cooldown"
        clock.advance(601.0)
        assert c.decide(Signals(users=150))[0].veto is None

    def test_unarmed_controller_never_proposes_a_split(self):
        c = make_controller(split_user_threshold=0, split_lock_wait_ms=0.0)
        for _ in range(5):
            assert c.decide(Signals(users=10 ** 9, lock_wait_ms=1e9)) == []

    def test_never_split_during_promotion(self):
        c = make_controller(act_ticks=1)
        out = c.decide(Signals(users=150, promoting=True))
        assert out[0].action == ACTION_SPLIT
        assert out[0].veto == "promotion"

    def test_never_split_over_an_unfinished_manifest(self):
        c = make_controller(act_ticks=1)
        out = c.decide(Signals(users=150, manifest=True))
        assert out[0].veto == "split-manifest"

    def test_vetoed_split_does_not_arm_the_cooldown(self):
        c = make_controller(act_ticks=1)
        assert c.decide(Signals(users=150, promoting=True))[0].veto == "promotion"
        # promotion over: the very next eligible intent acts (no cooldown
        # was burned on the vetoed one)
        assert c.decide(Signals(users=150))[0].veto is None


# --- lane drain / re-admit hysteresis ----------------------------------------


class TestLaneDecision:
    def test_open_must_persist_before_drain(self):
        clock = FakeClock()
        c = make_controller(clock)
        assert c.decide(Signals(lanes=[lane("0", "open")])) == []
        clock.advance(5.0)
        assert c.decide(Signals(lanes=[lane("0", "open")])) == []
        clock.advance(6.0)  # 11s total >= lane_open_after_s
        out = c.decide(Signals(lanes=[lane("0", "open")]))
        assert [d.action for d in out] == [ACTION_LANE_DRAIN]
        assert out[0].target == "0"
        assert out[0].veto is None

    def test_breaker_reclose_resets_open_persistence(self):
        clock = FakeClock()
        c = make_controller(clock)
        c.decide(Signals(lanes=[lane("0", "open")]))
        clock.advance(8.0)
        c.decide(Signals(lanes=[lane("0", "closed")]))  # recovered
        clock.advance(8.0)
        # re-opened: persistence clock starts over
        assert c.decide(Signals(lanes=[lane("0", "open")])) == []

    def test_readmit_needs_closed_ticks_and_cooldown(self):
        clock = FakeClock()
        c = make_controller(clock, lane_open_after_s=1.0)
        clock.advance(2.0)
        c.decide(Signals(lanes=[lane("0", "open")]))
        clock.advance(2.0)
        assert c.decide(Signals(lanes=[lane("0", "open")]))[0].action == \
            ACTION_LANE_DRAIN
        # drained now; breaker closes through probes but the cooldown
        # has not elapsed
        drained = [lane("0", "closed", drained=True)]
        assert c.decide(Signals(lanes=drained)) == []
        assert c.decide(Signals(lanes=drained)) == []
        clock.advance(31.0)  # past lane_cooldown_s; 2 closed ticks seen
        out = c.decide(Signals(lanes=drained))
        assert [d.action for d in out] == [ACTION_LANE_READMIT]
        assert out[0].veto is None

    def test_still_open_drained_lane_never_readmits(self):
        clock = FakeClock()
        c = make_controller(clock, lane_open_after_s=1.0)
        clock.advance(2.0)
        c.decide(Signals(lanes=[lane("0", "open")]))
        clock.advance(2.0)
        c.decide(Signals(lanes=[lane("0", "open")]))
        clock.advance(100.0)
        for _ in range(10):
            assert c.decide(
                Signals(lanes=[lane("0", "open", drained=True)])
            ) == []


# --- admission bias ----------------------------------------------------------


class FakeAdmission:
    def __init__(self, cap=3.0):
        self.level_cap = cap
        self.calls = []

    def set_level_cap(self, cap):
        self.level_cap = cap
        self.calls.append(cap)
        return cap


class TestAdmissionDecision:
    def test_paging_shrinks_after_act_ticks(self):
        c = make_controller()
        c.admission = FakeAdmission()
        paging = Signals(paging=True)
        assert c.decide(paging) == []
        assert c.decide(paging) == []
        out = c.decide(paging)
        assert [d.action for d in out] == [ACTION_ADMISSION_SHRINK]
        assert out[0].detail == {"cap": 3.0, "new_cap": 2.0}

    def test_clear_restores_after_clear_ticks(self):
        clock = FakeClock()
        c = make_controller(clock)
        c.admission = FakeAdmission(cap=2.0)
        clear = Signals(paging=False)
        assert c.decide(clear) == []
        out = c.decide(clear)
        assert [d.action for d in out] == [ACTION_ADMISSION_RESTORE]
        assert out[0].detail == {"cap": 2.0, "new_cap": 3.0}

    def test_shrink_floor_is_the_verify_tier(self):
        c = make_controller(act_ticks=1)
        c.admission = FakeAdmission(cap=1.0)  # already at MIN_LEVEL
        for _ in range(5):
            assert c.decide(Signals(paging=True)) == []

    def test_full_cap_never_restores(self):
        c = make_controller(clear_ticks=1)
        c.admission = FakeAdmission(cap=3.0)
        for _ in range(5):
            assert c.decide(Signals(paging=False)) == []

    def test_admission_cooldown_spaces_shrinks(self):
        clock = FakeClock()
        c = make_controller(clock, act_ticks=1)
        c.admission = FakeAdmission()
        assert c.decide(Signals(paging=True))[0].veto is None
        c.admission.level_cap = 2.0
        assert c.decide(Signals(paging=True))[0].veto == "cooldown"
        clock.advance(16.0)
        assert c.decide(Signals(paging=True))[0].veto is None


# --- single-action rail ------------------------------------------------------


class TestSingleActionRail:
    def test_second_armed_decision_same_tick_waits(self):
        clock = FakeClock()
        c = make_controller(clock, lane_open_after_s=1.0, act_ticks=1)
        c.admission = FakeAdmission()
        # warm the lane-open persistence
        c.decide(Signals(lanes=[lane("0", "open")]))
        clock.advance(2.0)
        # this tick arms BOTH a lane drain and an admission shrink
        out = c.decide(Signals(lanes=[lane("0", "open")], paging=True))
        assert [d.action for d in out] == [
            ACTION_LANE_DRAIN, ACTION_ADMISSION_SHRINK,
        ]
        assert out[0].veto is None
        assert out[1].veto == "single-action"

    def test_runner_up_keeps_eligibility_and_fires_next_tick(self):
        """REGRESSION (review): only the SELECTED action of a tick may
        consume its cooldown + hysteresis.  The runner-up vetoed as
        ``single-action`` never ran — if arming had already stamped its
        cooldown and reset its hot streak (as it once did inside the
        ``_decide_*`` helpers), the deferred action would re-pay a full
        cooldown plus ``act_ticks`` of re-accumulation for nothing."""
        clock = FakeClock()
        c = make_controller(clock, lane_open_after_s=1.0, act_ticks=2)
        c.admission = FakeAdmission()
        # warm both triggers: paging hot tick 1 of 2, lane-open persistence
        c.decide(Signals(lanes=[lane("0", "open")], paging=True))
        clock.advance(2.0)
        out = c.decide(Signals(lanes=[lane("0", "open")], paging=True))
        assert [d.action for d in out] == [
            ACTION_LANE_DRAIN, ACTION_ADMISSION_SHRINK,
        ]
        assert out[0].veto is None
        assert out[1].veto == "single-action"
        # the vetoed shrink consumed NOTHING: no admission cooldown was
        # stamped, its hot streak survived — it fires on the very next
        # tick instead of waiting out 15 s + 2 fresh hot ticks
        out = c.decide(Signals(paging=True))
        assert [d.action for d in out] == [ACTION_ADMISSION_SHRINK]
        assert out[0].veto is None

    def test_action_in_flight_vetoes_everything(self):
        clock = FakeClock()
        c = make_controller(clock, act_ticks=1, lane_open_after_s=1.0)
        c.admission = FakeAdmission()
        c.decide(Signals(lanes=[lane("0", "open")]))
        clock.advance(2.0)
        c.acting = True
        out = c.decide(
            Signals(users=150, lanes=[lane("0", "open")], paging=True)
        )
        assert len(out) == 3
        assert all(d.veto == "action-in-flight" for d in out)


# --- dry-run parity ----------------------------------------------------------


class FakeRouter:
    def __init__(self, lanes):
        self.rows = lanes
        self.drained = []
        self.readmitted = []

    def lane_states(self):
        return [dict(r) for r in self.rows]

    def drain_lane(self, label):
        self.drained.append(label)
        for r in self.rows:
            if r["lane"] == label:
                r["drained"] = True
        return True

    def readmit_lane(self, label):
        self.readmitted.append(label)
        for r in self.rows:
            if r["lane"] == label:
                r["drained"] = False
        return True


def _scripted(c: FleetController, script):
    """Run tick() over a list of Signals, injecting each via collect."""
    rows = []
    for sig in script:
        c.collect = lambda s=sig: s  # type: ignore[method-assign]
        rows.extend(run(c.tick()))
    return rows


class TestDryRunParity:
    def _script(self):
        hot = lambda: Signals(lanes=[lane("0", "open")], paging=True)  # noqa: E731
        return [hot() for _ in range(6)]

    def test_identical_decision_stream_no_action(self):
        """The parity contract: fed the SAME signal stream on the same
        clock, a dry-run controller and a live controller emit identical
        decisions (action, target, reason, veto) — only ``dry_run`` /
        ``fired`` differ, and only the live one calls an actuator."""
        script = [
            Signals(lanes=[lane("0", "open")], paging=True),
            Signals(lanes=[lane("0", "open")], paging=True),
            Signals(lanes=[lane("0", "open")], paging=False),
            Signals(lanes=[lane("0", "closed")], paging=False),
        ]
        # the admission cap is itself a signal the live actuator mutates,
        # so parity requires pinning the plane: this fake records the
        # actuator calls without changing what the next tick reads
        class PinnedAdmission(FakeAdmission):
            def set_level_cap(self, cap):
                self.calls.append(cap)
                return cap

        decided = {}
        routers = {}
        admissions = {}
        for mode in (True, False):
            clock = FakeClock()
            c = make_controller(
                clock, dry_run=mode, act_ticks=1, lane_open_after_s=1.0,
            )
            router = FakeRouter([lane("0", "open")])
            c.router = router
            c.admission = PinnedAdmission()
            routers[mode] = router
            admissions[mode] = c.admission
            out = []
            for sig in script:
                clock.advance(2.0)
                c.collect = lambda s=sig: s  # type: ignore[method-assign]
                out.extend(run(c.tick()))
            decided[mode] = out
        dry, live = decided[True], decided[False]
        # same decisions in the same order, modulo the mode markers
        assert [(d.action, d.target, d.reason, d.veto) for d in dry] == \
            [(d.action, d.target, d.reason, d.veto) for d in live]
        assert len(dry) > 0
        assert all(d.dry_run for d in dry)
        assert not any(d.dry_run for d in live)
        # dry run provably took no action...
        assert routers[True].drained == []
        assert admissions[True].calls == []
        assert not any(d.fired for d in dry)
        # ...while live mode drove the actuators
        assert routers[False].drained == ["0"]
        assert admissions[False].calls != []
        assert any(d.fired for d in live)

    def test_decision_events_flow_in_both_modes(self):
        for mode in (True, False):
            get_tracer().clear()
            clock = FakeClock()
            c = make_controller(
                clock, dry_run=mode, act_ticks=1, lane_open_after_s=1.0,
            )
            c.router = FakeRouter([lane("0", "open")])
            clock.advance(2.0)
            run(c.tick())
            clock.advance(2.0)
            run(c.tick())
            events = [
                t for t in get_tracer().completed()
                if t.name == DECISION_EVENT
            ]
            assert events, f"no decision events in dry_run={mode}"
            attrs = events[-1].spans[0].attrs
            assert attrs["action"] == ACTION_LANE_DRAIN
            assert attrs["dry_run"] is mode
            assert attrs["fired"] is (not mode)

    def test_status_ring_is_bounded(self):
        c = make_controller(act_ticks=1, decision_ring=4, lane_open_after_s=0.1)
        c.acting = True  # every decision vetoes, none mutate lanes
        clock = c._clock
        for i in range(10):
            c.collect = lambda i=i: Signals(  # type: ignore[method-assign]
                users=150, manifest=True,
            )
            run(c.tick())
        s = c.status()
        assert len(s["decisions"]) <= 4
        assert s["ticks"] == 10


# --- live actuators through tick() -------------------------------------------


class TestLiveActuation:
    def test_lane_drain_then_readmit_through_real_tick(self):
        clock = FakeClock()
        c = make_controller(
            clock, dry_run=False, act_ticks=1, clear_ticks=1,
            lane_open_after_s=1.0, lane_cooldown_s=5.0,
        )
        router = FakeRouter([lane("0", "open"), lane("1", "closed")])
        c.router = router
        run(c.tick())          # open seen, persistence starts
        clock.advance(2.0)
        run(c.tick())          # drain fires
        assert router.drained == ["0"]
        assert c.status()["drained_lanes"] == ["0"]
        # brownout ends: breaker re-closes via its probe traffic
        router.rows[0]["breaker"] = "closed"
        clock.advance(6.0)     # past lane_cooldown_s
        run(c.tick())          # closed tick #1 == clear_ticks -> readmit
        assert router.readmitted == ["0"]
        assert c.status()["drained_lanes"] == []

    def test_admission_cap_applied_and_restored(self):
        clock = FakeClock()
        c = make_controller(
            clock, dry_run=False, act_ticks=1, clear_ticks=1,
            admission_cooldown_s=1.0,
        )
        c.admission = FakeAdmission()
        c.collect = lambda: Signals(paging=True)  # type: ignore[method-assign]
        run(c.tick())
        assert c.admission.calls == [2.0]
        clock.advance(2.0)
        run(c.tick())
        assert c.admission.calls == [2.0, 1.0]
        clock.advance(2.0)
        c.collect = lambda: Signals(paging=False)  # type: ignore[method-assign]
        run(c.tick())
        assert c.admission.calls == [2.0, 1.0, 2.0]

    def test_actuator_error_surfaces_as_veto_and_releases_the_rail(self):
        clock = FakeClock()
        c = make_controller(clock, dry_run=False, act_ticks=1,
                            lane_open_after_s=1.0)

        class BoomRouter(FakeRouter):
            def drain_lane(self, label):
                raise RuntimeError("boom")

        c.router = BoomRouter([lane("0", "open")])
        run(c.tick())
        clock.advance(2.0)
        out = run(c.tick())
        assert out[0].veto.startswith("actuator-error")
        assert not out[0].fired
        assert c.acting is False

    def test_drain_error_rolls_back_and_retries_after_short_backoff(self):
        """REGRESSION (review): an actuator exception must give back the
        cooldown + bookkeeping the commit consumed — the drain never
        happened, so the lane must not read as drained, and the retry
        waits ``error_backoff_s``, not a full action cooldown."""
        clock = FakeClock()
        c = make_controller(clock, dry_run=False, act_ticks=1,
                            lane_open_after_s=1.0, error_backoff_s=5.0)

        class FlakyRouter(FakeRouter):
            broken = True

            def drain_lane(self, label):
                if self.broken:
                    raise RuntimeError("boom")
                return super().drain_lane(label)

        router = FlakyRouter([lane("0", "open")])
        c.router = router
        run(c.tick())                     # open seen, persistence starts
        clock.advance(2.0)
        out = run(c.tick())               # drain fires, actuator raises
        assert out[0].veto.startswith("actuator-error")
        # rollback: the lane is NOT drained, its open persistence survived
        assert c.status()["drained_lanes"] == []
        # ... and the retry is gated on the SHORT error backoff: armed
        # (persistence intact) but cooled within the window
        clock.advance(1.0)
        assert run(c.tick())[0].veto == "cooldown"
        clock.advance(5.0)                # past error_backoff_s
        router.broken = False
        out = run(c.tick())
        assert out[0].fired
        assert router.drained == ["0"]
        assert c.status()["drained_lanes"] == ["0"]

    def test_readmit_error_keeps_the_lane_tracked_as_drained(self):
        clock = FakeClock()
        c = make_controller(clock, dry_run=False, act_ticks=1,
                            clear_ticks=1, lane_open_after_s=1.0,
                            lane_cooldown_s=5.0, error_backoff_s=3.0)

        class FlakyRouter(FakeRouter):
            broken = False

            def readmit_lane(self, label):
                if self.broken:
                    raise RuntimeError("boom")
                return super().readmit_lane(label)

        router = FlakyRouter([lane("0", "open")])
        c.router = router
        run(c.tick())
        clock.advance(2.0)
        run(c.tick())                     # drain fires for real
        assert router.drained == ["0"]
        router.rows[0]["breaker"] = "closed"
        router.broken = True
        clock.advance(6.0)                # past lane_cooldown_s
        out = run(c.tick())               # readmit fires, actuator raises
        assert out[0].veto.startswith("actuator-error")
        # rollback: the lane is STILL drained (the readmit never happened
        # in the router) — forgetting it here would strand it forever
        assert c.status()["drained_lanes"] == ["0"]
        clock.advance(4.0)                # past error_backoff_s
        router.broken = False
        out = run(c.tick())
        assert out[0].fired
        assert router.readmitted == ["0"]
        assert c.status()["drained_lanes"] == []

    def test_split_error_backs_off_short_not_the_full_cooldown(self):
        """A transient split-actuator failure must not burn the 600 s
        split cooldown: the rollback restores the hot streak and arms
        only ``error_backoff_s``."""
        clock = FakeClock()
        c = make_controller(clock, dry_run=False, act_ticks=2,
                            error_backoff_s=5.0)
        # no fleet attached: the split actuator raises on first touch
        c.collect = lambda: Signals(users=150)  # type: ignore[method-assign]
        assert run(c.tick()) == []        # hot tick 1 of 2
        out = run(c.tick())               # hot tick 2: fires, raises
        assert out[0].veto.startswith("actuator-error")
        assert c._split_hot == 2          # rollback kept the streak
        clock.advance(1.0)
        assert run(c.tick())[0].veto == "cooldown"
        clock.advance(5.0)                # past error_backoff_s — 594 s
                                          # BEFORE split_cooldown_s would
                                          # have released it
        out = run(c.tick())
        assert out[0].veto.startswith("actuator-error")  # retried


# --- the live split (fast storm leg: split under concurrent traffic) ---------


async def _seed_live(n_users: int):
    state = ServerState()
    for i in range(n_users):
        await state.register_user(
            UserData(f"user-{i:03d}", make_statement(), 1)
        )
    return state


class TestLiveSplit:
    N = 30

    def test_live_split_disjoint_exhaustive(self, tmp_path):
        async def main():
            from cpzk_tpu.durability.recovery import recover_state

            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            state = await _seed_live(self.N)
            fleet = FleetRouter(PartitionMap.load(map_path), 0,
                                map_path=map_path)
            report = await run_live_split(
                map_path=map_path, source=0, new_address="127.0.0.1:2",
                state=state, fleet=fleet, segment_bytes=512,
            )
            assert report["new_version"] == 2
            assert report["moved_users"] == report["dropped_users"] > 0
            assert fleet.map.version == 2  # adopted in-process
            tgt = ServerState()
            await recover_state(
                tgt, report["target_state_file"],
                report["target_state_file"] + ".wal",
            )
            newmap = PartitionMap.load(map_path)
            live = {u for sh in state._shards for u in sh._users}
            moved = {u for sh in tgt._shards for u in sh._users}
            assert not (live & moved)
            assert live | moved == {f"user-{i:03d}" for i in range(self.N)}
            for uid in live:
                assert newmap.partition_for(uid).index == 0
            for uid in moved:
                assert newmap.partition_for(uid).index == 1
            assert not os.path.exists(manifest_path(map_path))

        run(main())

    def test_live_split_refuses_over_existing_manifest(self, tmp_path):
        async def main():
            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            with open(manifest_path(map_path), "w") as f:
                f.write("{}")
            state = await _seed_live(4)
            with pytest.raises(SplitError, match="manifest already exists"):
                await run_live_split(
                    map_path=map_path, source=0,
                    new_address="127.0.0.1:2", state=state,
                )

        run(main())

    def test_split_under_concurrent_traffic_zero_acked_loss(self, tmp_path):
        """The fast leg of the storm scenario: registrations keep landing
        while the controller splits the partition live.  Every
        acknowledged write must exist on exactly one partition
        afterwards — the no-await critical section makes this structural,
        and this test would catch anyone adding an await to it."""

        async def main():
            from cpzk_tpu.durability.recovery import recover_state

            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            state = await _seed_live(self.N)
            fleet = FleetRouter(PartitionMap.load(map_path), 0,
                                map_path=map_path)
            acked: list[str] = []
            redirected: list[str] = []
            stop = asyncio.Event()

            async def traffic():
                # the daemon's service layer checks ownership against the
                # live map BEFORE touching state (a non-owned user gets a
                # redirect, never an ack) — emulate that gate here, so an
                # "ack" below means what the daemon's ack means
                i = self.N
                stmt = make_statement()  # one statement: cheap loop
                while not stop.is_set():
                    uid = f"user-{i:03d}"
                    if fleet.map.partition_for(uid).index == fleet.self_index:
                        await state.register_user(UserData(uid, stmt, 1))
                        acked.append(uid)  # acknowledged
                    else:
                        redirected.append(uid)
                    i += 1
                    await asyncio.sleep(0)

            writer = asyncio.create_task(traffic())
            await asyncio.sleep(0.05)
            report = await run_live_split(
                map_path=map_path, source=0, new_address="127.0.0.1:2",
                state=state, fleet=fleet, segment_bytes=512,
            )
            await asyncio.sleep(0.05)
            stop.set()
            await writer
            tgt = ServerState()
            await recover_state(
                tgt, report["target_state_file"],
                report["target_state_file"] + ".wal",
            )
            live = {u for sh in state._shards for u in sh._users}
            moved = {u for sh in tgt._shards for u in sh._users}
            assert not (live & moved)
            # ZERO acked-write loss: every acknowledged registration
            # exists on exactly one partition afterwards
            lost = [u for u in acked if u not in live and u not in moved]
            assert lost == [], f"acked writes lost: {lost[:5]}"
            assert len(acked) > 0
            # the flip happened mid-traffic: some post-flip writes were
            # redirected to the new owner (proves the gate saw v2 live)
            assert len(redirected) > 0

        run(main())

    def test_controller_fires_the_live_split(self, tmp_path):
        """End to end through tick(): signals over threshold for
        act_ticks ticks -> a real in-process split, visible in the
        decision ring and the fleet map."""

        async def main():
            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            state = await _seed_live(self.N)
            fleet = FleetRouter(PartitionMap.load(map_path), 0,
                                map_path=map_path)
            clock = FakeClock()
            c = FleetController(
                ControllerSettings(
                    enabled=True, dry_run=False, act_ticks=2,
                    split_user_threshold=10,
                    split_target_address="127.0.0.1:2",
                ),
                state=state, fleet=fleet, clock=clock, wall=lambda: 0.0,
                segment_bytes=512,
            )
            labels = {"action": "split", "outcome": "fired"}
            before = metrics.read("fleet.controller.decisions",
                                  labels=labels)
            assert await c.tick() == []     # hot tick 1 of 2
            out = await c.tick()            # hot tick 2: split fires
            assert [d.action for d in out] == [ACTION_SPLIT]
            assert out[0].fired
            assert out[0].detail["report"]["new_version"] == 2
            assert fleet.map.version == 2
            assert metrics.read("fleet.controller.decisions",
                                labels=labels) == before + 1
            remaining = sum(r["users"] for r in state.shard_stats())
            assert 0 < remaining < self.N
            # the next hot streak is cooled down AND manifest-free
            assert (await c.tick()) == []  # streak restarted post-fire
            clock.advance(1.0)
            out = await c.tick()
            assert out and out[0].veto == "cooldown"

        run(main())

    def test_owner_fence_blocks_writer_straddling_the_flip(self, tmp_path):
        """REGRESSION (review): a handler that checked ownership at entry,
        awaited (verify_proof parks on the dynamic batcher), and only then
        minted its session could land the write AFTER the split's map
        flip — on the source's post-export state, where ``drop_users``
        discards it while the client holds a success and a token valid on
        NEITHER partition.  With the write-time owner fence installed the
        late write raises ``WrongPartition`` INSTEAD of acking: the
        client gets a redirect and retries at the new owner — an
        acknowledged write is never silently lost."""

        async def main():
            from cpzk_tpu.errors import InvalidParams, WrongPartition

            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            state = await _seed_live(self.N)
            fleet = FleetRouter(PartitionMap.load(map_path), 0,
                                map_path=map_path)

            def owns(uid):
                return fleet.map.partition_for(uid).index == fleet.self_index

            # the daemon's fence: ownership under the LIVE map, re-asked
            # synchronously at write time
            state.attach_owner_fence(
                lambda uid: None if owns(uid)
                else f"wrong partition: user '{uid}' moved"
            )
            # pick a seeded user the split WILL move: the successor map
            # is a pure function of (current map, source, new address)
            successor, _ = fleet.map.split(0, "127.0.0.1:2")
            moving = next(
                f"user-{i:03d}" for i in range(self.N)
                if successor.partition_for(f"user-{i:03d}").index == 1
            )
            tok = state.tag_session_token(moving, "t" * 40)
            in_await = asyncio.Event()
            resume = asyncio.Event()

            async def straddling_handler():
                assert owns(moving)        # entry check passes pre-flip...
                in_await.set()
                await resume.wait()        # ...the batcher await, during
                                           # which the flip lands...
                await state.create_session(tok, moving)  # ...the late write

            writer = asyncio.create_task(straddling_handler())
            await in_await.wait()
            report = await run_live_split(
                map_path=map_path, source=0, new_address="127.0.0.1:2",
                state=state, fleet=fleet, segment_bytes=512,
            )
            assert report["moved_users"] > 0
            assert not owns(moving)        # the flip took it away
            resume.set()
            with pytest.raises(WrongPartition, match="wrong partition"):
                await writer               # the ack NEVER happens
            # ...and the fenced write left no trace: the token is invalid
            # on the source (and was never exported, so it exists on the
            # target only if the client retries there — honestly)
            with pytest.raises(InvalidParams):
                await state.validate_session(tok)

        run(main())


# --- the write-time partition-ownership fence (ServerState.owner_fence) ------


class TestOwnerFence:
    """State-level contract: every acknowledged user-keyed mutation
    re-checks ownership INSIDE the shard lock, in the same synchronous
    section as the mutation; reads and challenge consumes stay unfenced
    on purpose (removing or reading a stale copy the split already
    exported cannot lose an acknowledged write)."""

    @staticmethod
    def _only(owner_uid):
        return lambda uid: (
            None if uid == owner_uid
            else f"wrong partition: user '{uid}' is not owned here"
        )

    def test_fence_rejects_every_acked_mutation(self):
        async def main():
            from cpzk_tpu.errors import WrongPartition

            state = ServerState()
            stmt = make_statement()
            await state.register_user(UserData("mine", stmt, 1))
            await state.register_user(UserData("moved", stmt, 1))
            tok = state.tag_session_token("moved", "s" * 40)
            await state.create_session(tok, "moved")
            state.attach_owner_fence(self._only("mine"))

            # register_user — fenced BEFORE the duplicate check, so a
            # stale post-flip copy answers redirect, not "already
            # registered"
            with pytest.raises(WrongPartition):
                await state.register_user(UserData("moved", stmt, 1))
            with pytest.raises(WrongPartition):
                await state.register_user(UserData("stranger", stmt, 1))
            assert "stranger" not in state._users
            # create_challenge
            with pytest.raises(WrongPartition):
                await state.create_challenge(
                    "moved", state.tag_challenge_id("moved", b"c" * 32)
                )
            # create_session — the scalar wrapper raises, the bulk form
            # reports the same message per-pair
            with pytest.raises(WrongPartition):
                await state.create_session(
                    state.tag_session_token("moved", "u" * 40), "moved"
                )
            msgs = await state.create_sessions([
                (state.tag_session_token("moved", "v" * 40), "moved"),
                (state.tag_session_token("mine", "w" * 40), "mine"),
            ])
            assert msgs[0].startswith("wrong partition")
            assert msgs[1] is None
            # revoke_session — revoking only the stale copy would ack a
            # revoke the new owner never saw
            with pytest.raises(WrongPartition):
                await state.revoke_session(tok)
            assert await state.validate_session(tok) == "moved"

        run(main())

    def test_consume_and_reads_stay_unfenced(self):
        async def main():
            state = ServerState()
            await state.register_user(UserData("moved", make_statement(), 1))
            cid = state.tag_challenge_id("moved", b"c" * 32)
            await state.create_challenge("moved", cid)
            tok = state.tag_session_token("moved", "t" * 40)
            await state.create_session(tok, "moved")
            # flip: this daemon owns nothing any more
            state.attach_owner_fence(lambda uid: "wrong partition: flipped")
            # an in-flight login still consumes its (stale) challenge —
            # the exported copy at the new owner is untouched, so the
            # retry there succeeds — and a held token still validates
            got = await state.consume_challenge(cid)
            assert got.user_id == "moved"
            assert await state.validate_session(tok) == "moved"

        run(main())

    def test_fenced_mutation_never_reaches_the_journal(self):
        async def main():
            from cpzk_tpu.errors import WrongPartition

            class FakeWal:
                def __init__(self):
                    self.records = []
                    self.seq = 0

                def append(self, rtype, payload):
                    self.records.append(rtype)
                    self.seq += 1

                def needs_sync(self):
                    return False

            state = ServerState()
            wal = FakeWal()
            state.attach_journal(wal)
            state.attach_owner_fence(lambda uid: "wrong partition: flipped")
            with pytest.raises(WrongPartition):
                await state.register_user(UserData("u", make_statement(), 1))
            msgs = await state.create_sessions(
                [(state.tag_session_token("u", "t" * 40), "u")]
            )
            assert msgs[0].startswith("wrong partition")
            # no WAL trace: replay/standby apply can never resurrect a
            # write that was never acknowledged
            assert wal.records == []

        run(main())


# --- dry-run controller against real planes (signal collection) --------------


class TestCollect:
    def test_collect_reads_state_slo_and_manifest(self, tmp_path):
        async def main():
            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            state = await _seed_live(8)
            fleet = FleetRouter(PartitionMap.load(map_path), 0,
                                map_path=map_path)

            class FakeSlo:
                def snapshot(self):
                    return {"rpcs": {"VerifyProof": {"paging": ["fast"]}}}

            c = FleetController(
                ControllerSettings(enabled=True),
                state=state, fleet=fleet, slo=FakeSlo(),
            )
            sig = c.collect()
            assert sig.users == 8
            assert sig.paging is True
            assert sig.manifest is False
            assert sig.promoting is False
            with open(manifest_path(map_path), "w") as f:
                f.write("{}")
            assert c.collect().manifest is True

        run(main())

    def test_collect_standby_reports_promoting(self):
        class FakeReplica:
            role = "standby"

        c = FleetController(ControllerSettings(), replica=FakeReplica())
        assert c.collect().promoting is True


# --- ingest crash-loop guard (fast leg of the crash-loop storm) --------------


class TestIngestCrashloopGuard:
    def _bare_supervisor(self, **kw):
        """IngestSupervisor death-handling state without the heavyweight
        __init__ (no pb2, no sockets): exactly the fields
        _on_shard_death and the respawn scheduler touch."""
        from cpzk_tpu.server.ingest import IngestSupervisor

        sup = IngestSupervisor.__new__(IngestSupervisor)
        sup.backoff_base_s = kw.get("backoff_base_s", 0.5)
        sup.backoff_max_s = kw.get("backoff_max_s", 30.0)
        sup.crashloop_deaths = kw.get("crashloop_deaths", 5)
        sup.crashloop_window_s = kw.get("crashloop_window_s", 60.0)
        sup._death_times = {}
        sup._respawn_at = {}
        sup._procs = {}
        sup._backoff_rng = random.Random(7)
        sup.shards = 1
        sup.respawns = 0
        sup.shard_stats = {0: {"shard": 0, "pid": None, "connected": False,
                               "respawns": 0, "crashloop": False}}
        return sup

    def test_backoff_ceiling_doubles_per_death(self):
        sup = self._bare_supervisor()
        sup._backoff_rng.uniform = lambda a, b: b  # pin jitter to ceiling
        delays = []
        for i in range(4):
            sup._on_shard_death(0, 111, -9, now=100.0 + i)
            delays.append(sup._respawn_at[0] - (100.0 + i))
            del sup._respawn_at[0]
        assert delays == [0.5, 1.0, 2.0, 4.0]

    def test_backoff_is_capped(self):
        sup = self._bare_supervisor(backoff_max_s=3.0, crashloop_deaths=99)
        sup._backoff_rng.uniform = lambda a, b: b
        for i in range(8):
            sup._on_shard_death(0, 111, -9, now=100.0 + i)
            delay = sup._respawn_at.pop(0) - (100.0 + i)
            assert delay <= 3.0

    def test_crashloop_gives_up_and_marks_statusz(self):
        sup = self._bare_supervisor(crashloop_deaths=3, crashloop_window_s=60)
        before = metrics.read("ingest.shard.crashloop")
        for i in range(3):
            sup._respawn_at.pop(0, None)
            sup._on_shard_death(0, 111, -9, now=100.0 + i)
        assert sup.shard_stats[0]["crashloop"] is True
        assert 0 not in sup._respawn_at            # never respawned again
        assert metrics.read("ingest.shard.crashloop") == before + 1
        assert sup.status()["crashloop_shards"] == 1

    def test_slow_deaths_outside_window_never_trip_the_guard(self):
        sup = self._bare_supervisor(crashloop_deaths=3, crashloop_window_s=10)
        for i in range(6):
            sup._respawn_at.pop(0, None)
            sup._on_shard_death(0, 111, -9, now=100.0 + 20.0 * i)
        assert sup.shard_stats[0]["crashloop"] is False
        assert 0 in sup._respawn_at


# --- client herd damping (fast leg of the herd-reconnect storm) --------------


class TestClientHerdDamping:
    def test_refresh_single_flight_coalesces(self):
        from cpzk_tpu.client.rpc import AuthClient

        async def main():
            pmap = PartitionMap.uniform(["127.0.0.1:1"])
            fetches = []

            async def fetch():
                fetches.append(1)
                await asyncio.sleep(0.02)
                return dataclass_replace_version(pmap, 5)

            client = AuthClient(
                "127.0.0.1:1", partition_map=pmap, map_refresh=fetch,
                refresh_jitter_s=0.0,
            )
            try:
                results = await asyncio.gather(
                    *[client._refresh_map() for _ in range(20)]
                )
                assert len(fetches) == 1       # one shared in-flight fetch
                assert client.refresh_coalesced == 19
                assert any(results)
                assert client.partition_map.version == 5
                # within the min interval: answered from the last fetch
                assert await client._refresh_map() is False
                assert len(fetches) == 1
            finally:
                await client.close()

        run(main())

    def test_reconnect_damping_spreads_the_herd(self):
        from cpzk_tpu.client.rpc import AuthClient

        async def main():
            client = AuthClient("127.0.0.1:1", reconnect_damp_s=0.05)
            try:
                loop = asyncio.get_running_loop()
                client._mark_down("127.0.0.1:1")
                t0 = loop.time()
                await client._damp_reconnect("127.0.0.1:1")
                assert client.reconnects_damped == 1
                # the mark cleared: steady-state traffic is never taxed
                await client._damp_reconnect("127.0.0.1:1")
                assert client.reconnects_damped == 1
            finally:
                await client.close()

        run(main())

    def test_stale_down_mark_is_ignored(self):
        from cpzk_tpu.client.rpc import AuthClient

        async def main():
            client = AuthClient("127.0.0.1:1", reconnect_damp_s=0.01)
            try:
                loop = asyncio.get_running_loop()
                client._addr_down["127.0.0.1:1"] = loop.time() - 10.0
                await client._damp_reconnect("127.0.0.1:1")
                assert client.reconnects_damped == 0
                assert "127.0.0.1:1" not in client._addr_down
            finally:
                await client.close()

        run(main())


def dataclass_replace_version(pmap: PartitionMap, version: int) -> PartitionMap:
    return PartitionMap(version, pmap.partitions)


# --- config surface ----------------------------------------------------------


class TestControllerConfig:
    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("SERVER_CONTROLLER_ENABLED", "true")
        monkeypatch.setenv("SERVER_CONTROLLER_DRY_RUN", "false")
        monkeypatch.setenv("SERVER_CONTROLLER_TICK_INTERVAL_MS", "250")
        monkeypatch.setenv("SERVER_CONTROLLER_SPLIT_USER_THRESHOLD", "5000")
        monkeypatch.setenv(
            "SERVER_CONTROLLER_SPLIT_TARGET_ADDRESS", "10.0.0.9:50051"
        )
        cfg = ServerConfig.from_env()
        assert cfg.controller.enabled is True
        assert cfg.controller.dry_run is False
        assert cfg.controller.tick_interval_ms == 250.0
        assert cfg.controller.split_user_threshold == 5000
        assert cfg.controller.split_target_address == "10.0.0.9:50051"

    def test_armed_split_without_target_rejected(self):
        cfg = ServerConfig()
        cfg.controller.enabled = True
        cfg.controller.split_user_threshold = 1000
        with pytest.raises(ValueError, match="split_target_address"):
            cfg.validate()

    def test_bad_hysteresis_rejected(self):
        cfg = ServerConfig()
        cfg.controller.act_ticks = 0
        with pytest.raises(ValueError, match="act_ticks"):
            cfg.validate()

    def test_error_backoff_env_override_and_validation(self, monkeypatch):
        monkeypatch.setenv("SERVER_CONTROLLER_ERROR_BACKOFF_S", "7.5")
        cfg = ServerConfig.from_env()
        assert cfg.controller.error_backoff_s == 7.5
        cfg = ServerConfig()
        cfg.controller.error_backoff_s = -1.0
        with pytest.raises(ValueError, match="cooldowns cannot be negative"):
            cfg.validate()

    def test_controller_config_keys_documented(self):
        """CI drift guard (pattern from test_opsplane.py): every
        [controller] knob ships in the TOML example, the .env example,
        and the operations-doc knob inventory."""
        import dataclasses
        import re
        from pathlib import Path

        root = Path(ROOT)
        docs = (root / "docs" / "operations.md").read_text()
        toml_text = (root / "config" / "server.toml.example").read_text()
        env_text = (root / ".env.example").read_text()
        keys = [f.name for f in dataclasses.fields(ControllerSettings)]
        assert keys
        m = re.search(r"^\[controller\]$", toml_text, re.M)
        assert m, "[controller] section missing from server.toml.example"
        body = toml_text[m.end():].split("\n[", 1)[0]
        for key in keys:
            assert re.search(rf"^{key}\s*=", body, re.M), (
                f"[controller] key {key!r} missing from server.toml.example"
            )
            assert f"SERVER_CONTROLLER_{key.upper()}" in env_text, (
                f"SERVER_CONTROLLER_{key.upper()} missing from .env.example"
            )
            assert f"`controller.{key}`" in docs, (
                f"`controller.{key}` missing from the docs/operations.md "
                "knob inventory"
            )


# --- full-scale storm legs (benches/bench_soak.py --storm, marked slow) ------


ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_storm(leg: str, port: int, ops_port: int, extra=()):
    """Run one bench storm leg as a subprocess; nonzero exit means an
    invariant (zero acked-write loss / bounded burn) was violated."""
    import json
    import subprocess
    import sys

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benches", "bench_soak.py"),
         "--storm", leg, "--port", str(port), "--ops-port", str(ops_port),
         *extra],
        capture_output=True, text=True, timeout=420, cwd=ROOT, env=env,
    )
    assert proc.returncode == 0, (
        f"storm {leg} violated an invariant:\n--- stdout\n"
        f"{proc.stdout[-2000:]}\n--- stderr\n{proc.stderr[-2000:]}"
    )
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["violations"] == []
    return report["legs"][leg]


@pytest.mark.slow
class TestStormSuiteFullScale:
    """The four failure storms at full scale — subprocess daemons, real
    SIGKILLs, tens of thousands of acked writes.  The fast structural
    versions of the same scenarios run in tier-1 above."""

    def test_storm_herd_full_scale(self):
        rep = _run_storm("herd", 50271, 9271, [
            "--storm-users", "20000", "--storm-clients", "8",
            "--storm-duration", "6",
        ])
        assert rep["sampled_users_lost"] == 0
        assert rep["recovery_ms"] is not None
        assert rep["refresh_coalesced"] > 0

    def test_storm_brownout_full_scale(self):
        rep = _run_storm("brownout", 50275, 9275)
        assert rep["dry_run_drain_proposed"] is True
        assert rep["actions_fired"].count("lane_drain") >= 1
        assert rep["actions_fired"].count("lane_readmit") >= 1
        assert rep["batches_verified"] > 0

    def test_storm_split_full_scale(self):
        rep = _run_storm("split", 50279, 9279, ["--storm-users", "5000"])
        assert rep["acked_during_storm"] > 0
        assert rep["redirected_after_flip"] > 0
        assert rep["map_version"] == 2

    def test_storm_crashloop_full_scale(self):
        rep = _run_storm("crashloop", 50283, 9283, ["--storm-users", "500"])
        assert rep["crashloop_tripped"] is True
        assert rep["post_crashloop_login_failures"] == 0
