"""Keccak / STROBE / Merlin-twin transcript tests
(mirrors reference src/primitives/transcript.rs:80-119 tests, plus
permutation validation against hashlib and the merlin crate's own
published test vector)."""

import hashlib

from cpzk_tpu.core.keccak import sha3_256
from cpzk_tpu.core.transcript import MerlinTranscript, Transcript


def test_keccak_permutation_via_sha3():
    for msg in [b"", b"abc", b"x" * 135, b"y" * 136, b"z" * 1000]:
        assert sha3_256(msg) == hashlib.sha3_256(msg).digest()


def test_merlin_crate_vector():
    """The merlin crate's 'equivalence' doc test vector — byte-identical
    framing is required for cross-verification against reference proofs."""
    t = MerlinTranscript(b"test protocol")
    t.append_message(b"some label", b"some data")
    challenge = t.challenge_bytes(b"challenge", 32)
    assert challenge.hex() == "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"


def test_challenge_scalar_deterministic():
    def build():
        t = Transcript()
        t.append_parameters(b"g", b"h")
        t.append_statement(b"y1", b"y2")
        t.append_commitment(b"r1", b"r2")
        return t.challenge_scalar()

    assert build() == build()


def test_challenge_scalar_different_inputs():
    t1 = Transcript()
    t1.append_commitment(b"r1", b"r2")
    t2 = Transcript()
    t2.append_commitment(b"r1_different", b"r2")
    assert t1.challenge_scalar() != t2.challenge_scalar()


def test_context_changes_challenge():
    t1 = Transcript()
    t1.append_context(b"ctx-a")
    t2 = Transcript()
    t2.append_context(b"ctx-b")
    t1.append_commitment(b"r1", b"r2")
    t2.append_commitment(b"r1", b"r2")
    assert t1.challenge_scalar() != t2.challenge_scalar()


def test_label_framing_not_concatenation():
    """Merlin length-prefixes messages: moving bytes between fields must
    change the challenge."""
    t1 = Transcript()
    t1.append_statement(b"ab", b"c")
    t2 = Transcript()
    t2.append_statement(b"a", b"bc")
    assert t1.challenge_scalar() != t2.challenge_scalar()


def test_pinned_transcript_vectors():
    """Frozen transcript behavior across the op surface (VERDICT r4 item 7
    scoped honestly: self-generated, provenance in the JSON — the external
    anchors remain the merlin doc vector above and the SHA3 differential).
    Any drift in label framing, STROBE op chaining, multi-squeeze state,
    context binding, or the scalar wide reduction fails here."""
    import json
    import os

    from cpzk_tpu.core.transcript import MerlinTranscript, Transcript
    from cpzk_tpu.core.ristretto import Ristretto255

    path = os.path.join(os.path.dirname(__file__), "vectors",
                        "transcript_vectors.json")
    with open(path, encoding="utf-8") as f:
        data = json.load(f)

    g = Ristretto255.element_to_bytes(Ristretto255.generator_g())
    h = Ristretto255.element_to_bytes(Ristretto255.generator_h())
    checked = 0
    for vec in data["vectors"]:
        if vec["kind"] == "merlin" and "messages" in vec:
            t = MerlinTranscript(b"cpzk-vector-test")
            for lbl, m in vec["messages"]:
                t.append_message(lbl.encode(), bytes.fromhex(m))
            for lbl, n in vec["challenges"]:
                assert t.challenge_bytes(lbl.encode(), n).hex() == \
                    vec["outputs"][lbl], vec["name"]
            checked += 1
        elif vec["kind"] == "merlin":  # append-after-squeeze
            t = MerlinTranscript(b"cpzk-vector-test")
            t.append_message(b"m", b"first")
            assert t.challenge_bytes(b"c1", 32).hex() == vec["outputs"]["c1"]
            t.append_message(b"m2", b"second")
            assert t.challenge_bytes(b"c2", 32).hex() == vec["outputs"]["c2"]
            checked += 1
        else:  # protocol layer
            t = Transcript()
            if vec["context"] is not None:
                t.append_context(bytes.fromhex(vec["context"]))
            t.append_parameters(g, h)
            t.append_statement(g, h)
            t.append_commitment(h, g)
            assert "%064x" % t.challenge_scalar().value == \
                vec["challenge_scalar"], vec["name"]
            checked += 1
    assert checked == len(data["vectors"]) == 9
