"""Keccak / STROBE / Merlin-twin transcript tests
(mirrors reference src/primitives/transcript.rs:80-119 tests, plus
permutation validation against hashlib and the merlin crate's own
published test vector)."""

import hashlib

from cpzk_tpu.core.keccak import sha3_256
from cpzk_tpu.core.transcript import MerlinTranscript, Transcript


def test_keccak_permutation_via_sha3():
    for msg in [b"", b"abc", b"x" * 135, b"y" * 136, b"z" * 1000]:
        assert sha3_256(msg) == hashlib.sha3_256(msg).digest()


def test_merlin_crate_vector():
    """The merlin crate's 'equivalence' doc test vector — byte-identical
    framing is required for cross-verification against reference proofs."""
    t = MerlinTranscript(b"test protocol")
    t.append_message(b"some label", b"some data")
    challenge = t.challenge_bytes(b"challenge", 32)
    assert challenge.hex() == "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"


def test_challenge_scalar_deterministic():
    def build():
        t = Transcript()
        t.append_parameters(b"g", b"h")
        t.append_statement(b"y1", b"y2")
        t.append_commitment(b"r1", b"r2")
        return t.challenge_scalar()

    assert build() == build()


def test_challenge_scalar_different_inputs():
    t1 = Transcript()
    t1.append_commitment(b"r1", b"r2")
    t2 = Transcript()
    t2.append_commitment(b"r1_different", b"r2")
    assert t1.challenge_scalar() != t2.challenge_scalar()


def test_context_changes_challenge():
    t1 = Transcript()
    t1.append_context(b"ctx-a")
    t2 = Transcript()
    t2.append_context(b"ctx-b")
    t1.append_commitment(b"r1", b"r2")
    t2.append_commitment(b"r1", b"r2")
    assert t1.challenge_scalar() != t2.challenge_scalar()


def test_label_framing_not_concatenation():
    """Merlin length-prefixes messages: moving bytes between fields must
    change the challenge."""
    t1 = Transcript()
    t1.append_statement(b"ab", b"c")
    t2 = Transcript()
    t2.append_statement(b"a", b"bc")
    assert t1.challenge_scalar() != t2.challenge_scalar()


class _SpecStrobe128:
    """Independent STROBE-128 duplex written from the STROBE v1.0.2 spec
    (sections 5.1-5.3, 6.2, 7: initialization, ``_begin_op``, duplexing),
    deliberately structured differently from ``core/strobe.py`` — state as
    25 keccak lanes with explicit byte packing rather than a 200-byte
    buffer — so a shared implementation quirk cannot hide in both.  Only
    the keccak permutation itself is shared, and that is anchored to
    hashlib separately (``test_keccak_permutation_via_sha3``).  VERDICT r4
    item 7: a second, spec-derived anchor for the transcript layer beyond
    the single merlin doc vector."""

    R = 166  # security level 128: R = 200 - 128/4 - 2

    def __init__(self, label: bytes):
        from cpzk_tpu.core.keccak import keccak_f1600

        self._f = keccak_f1600
        # spec 5.1: S = F(pad-start bytes || "STROBEv1.0.2"); the 6-byte
        # prefix is the cSHAKE-style domain [1, R+2, 1, 0, 1, 96]
        init = bytes([0x01, self.R + 2, 0x01, 0x00, 0x01, 0x60])
        init += b"STROBEv1.0.2"
        init += bytes(200 - len(init))
        self.lanes = self._f(
            [int.from_bytes(init[i * 8:(i + 1) * 8], "little")
             for i in range(25)])
        self.off = 0          # spec: pos within the rate
        self.begin = 0        # spec: pos_begin
        self.flags = None
        # operate(meta_ad, label) per spec 5.1 "initial AD of the
        # protocol label as meta-AD"
        self.operate(0x10 | 0x02, label)

    # -- lane-level byte access (the structural difference) --
    def _get(self, i: int) -> int:
        return (self.lanes[i // 8] >> (8 * (i % 8))) & 0xFF

    def _xor(self, i: int, b: int) -> None:
        self.lanes[i // 8] ^= b << (8 * (i % 8))

    def _set(self, i: int, b: int) -> None:
        lane = self.lanes[i // 8]
        shift = 8 * (i % 8)
        self.lanes[i // 8] = (lane & ~(0xFF << shift)) | (b << shift)

    def _runf(self) -> None:
        # spec 6.2: absorb pos_begin and the padding byte, permute
        self._xor(self.off, self.begin)
        self._xor(self.off + 1, 0x04)
        self._xor(self.R + 1, 0x80)
        self.lanes = self._f(self.lanes)
        self.off = 0
        self.begin = 0

    def operate(self, flags: int, data: bytes, n: int = 0) -> bytes | None:
        """One whole (non-continued) operation per spec 7: frame then
        duplex.  ``n`` nonzero = output op (PRF)."""
        # spec 6.3 _begin_op: duplex([pos_begin, flags]) with pos_begin
        # recorded BEFORE the frame bytes are absorbed
        old = self.begin
        self.begin = self.off + 1
        self.flags = flags
        for b in (old, flags):
            self._xor(self.off, b)
            self.off += 1
            if self.off == self.R:
                self._runf()
        if flags & (0x04 | 0x20) and self.off != 0:  # C or K: align to F
            self._runf()
        if n:  # squeeze (overwrite mode: output then zero, spec 7 PRF)
            out = bytearray()
            for _ in range(n):
                out.append(self._get(self.off))
                self._set(self.off, 0)
                self.off += 1
                if self.off == self.R:
                    self._runf()
            return bytes(out)
        for b in data:  # absorb
            self._xor(self.off, b)
            self.off += 1
            if self.off == self.R:
                self._runf()
        return None

    # merlin's three ops
    def meta_ad(self, d: bytes) -> None:
        self.operate(0x10 | 0x02, d)

    def ad(self, d: bytes) -> None:
        self.operate(0x02, d)

    def prf(self, n: int) -> bytes:
        return self.operate(0x01 | 0x02 | 0x04, b"", n)


def test_strobe_spec_twin_differential():
    """Randomized op sequences through the production Strobe128 and the
    spec-derived twin above must agree byte-for-byte — including ops that
    cross the 166-byte rate boundary, long squeezes, and absorb-after-
    squeeze chaining that the merlin doc vector never exercises."""
    import random

    from cpzk_tpu.core.strobe import Strobe128

    rng = random.Random(0xC0FFEE)
    for trial in range(20):
        label = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 40)))
        prod = Strobe128(label)
        spec = _SpecStrobe128(label)
        for step in range(rng.randrange(2, 12)):
            op = rng.randrange(3)
            if op == 0:
                d = bytes(rng.randrange(256)
                          for _ in range(rng.randrange(0, 400)))
                prod.meta_ad(d, False)
                spec.meta_ad(d)
            elif op == 1:
                d = bytes(rng.randrange(256)
                          for _ in range(rng.randrange(0, 400)))
                prod.ad(d, False)
                spec.ad(d)
            else:
                n = rng.randrange(1, 300)
                a, b = prod.prf(n, False), spec.prf(n)
                assert a == b, f"trial {trial} step {step}: PRF diverged"
        # final drain: states must still be aligned
        assert prod.prf(64, False) == spec.prf(64), f"trial {trial} drain"


def test_strobe_spec_twin_merlin_vector():
    """The spec twin reproduces the merlin doc vector through merlin's own
    framing (meta-AD of 'Merlin v1.0', dom-sep appends, PRF challenge) —
    tying the spec-derived STROBE directly to the external anchor."""
    # merlin framing: Transcript::new(label) = Strobe128("Merlin v1.0")
    # then append_message(b"dom-sep", label); append_message(label, msg) =
    # meta_ad(label || LE32(len(msg))) then ad(msg); challenge_bytes =
    # meta_ad(label || LE32(n)) then prf(n)
    spec2 = _SpecStrobe128(b"Merlin v1.0")
    for label, msg in ((b"dom-sep", b"test protocol"),
                       (b"some label", b"some data")):
        spec2.meta_ad(label + len(msg).to_bytes(4, "little"))
        spec2.ad(msg)
    spec2.meta_ad(b"challenge" + (32).to_bytes(4, "little"))
    out = spec2.prf(32)
    assert out.hex() == \
        "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"


def test_pinned_transcript_vectors():
    """Frozen transcript behavior across the op surface (VERDICT r4 item 7
    scoped honestly: self-generated, provenance in the JSON — the external
    anchors remain the merlin doc vector above and the SHA3 differential).
    Any drift in label framing, STROBE op chaining, multi-squeeze state,
    context binding, or the scalar wide reduction fails here."""
    import json
    import os

    from cpzk_tpu.core.transcript import MerlinTranscript, Transcript
    from cpzk_tpu.core.ristretto import Ristretto255

    path = os.path.join(os.path.dirname(__file__), "vectors",
                        "transcript_vectors.json")
    with open(path, encoding="utf-8") as f:
        data = json.load(f)

    g = Ristretto255.element_to_bytes(Ristretto255.generator_g())
    h = Ristretto255.element_to_bytes(Ristretto255.generator_h())
    checked = 0
    for vec in data["vectors"]:
        if vec["kind"] == "merlin" and "messages" in vec:
            t = MerlinTranscript(b"cpzk-vector-test")
            for lbl, m in vec["messages"]:
                t.append_message(lbl.encode(), bytes.fromhex(m))
            for lbl, n in vec["challenges"]:
                assert t.challenge_bytes(lbl.encode(), n).hex() == \
                    vec["outputs"][lbl], vec["name"]
            checked += 1
        elif vec["kind"] == "merlin":  # append-after-squeeze
            t = MerlinTranscript(b"cpzk-vector-test")
            t.append_message(b"m", b"first")
            assert t.challenge_bytes(b"c1", 32).hex() == vec["outputs"]["c1"]
            t.append_message(b"m2", b"second")
            assert t.challenge_bytes(b"c2", 32).hex() == vec["outputs"]["c2"]
            checked += 1
        else:  # protocol layer
            t = Transcript()
            if vec["context"] is not None:
                t.append_context(bytes.fromhex(vec["context"]))
            t.append_parameters(g, h)
            t.append_statement(g, h)
            t.append_commitment(h, g)
            assert "%064x" % t.challenge_scalar().value == \
                vec["challenge_scalar"], vec["name"]
            checked += 1
    assert checked == len(data["vectors"]) == 9


def test_device_challenges_env_warns_once(monkeypatch):
    """ADVICE r5 satellite: CPZK_DEVICE_CHALLENGES=1 deployments must be
    told (once) that the device-challenge path was removed after
    calibration, instead of silently falling through to the host pool."""
    import warnings

    import pytest

    from cpzk_tpu.core import transcript as tr

    def derive():
        w = b"\x01" * 32
        return tr.derive_challenges_batch([None], [w], [w], [w], [w], [w], [w])

    monkeypatch.setenv("CPZK_DEVICE_CHALLENGES", "1")
    monkeypatch.setattr(tr, "_DEVICE_CHALLENGES_WARNED", False)
    with pytest.warns(UserWarning, match="device-challenge"):
        assert len(derive()) == 1
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a second warning would raise
        assert len(derive()) == 1

    # unset env: no warning at all
    monkeypatch.delenv("CPZK_DEVICE_CHALLENGES")
    monkeypatch.setattr(tr, "_DEVICE_CHALLENGES_WARNED", False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert len(derive()) == 1
