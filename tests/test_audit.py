"""Audit subsystem tests: the proof log (WAL-framed, append-only), the
bulk replay pipeline (resumable cursor, byte-exact SIGKILL resume,
mismatch detection), the Schnorr-signed report (offline verification,
single-flipped-byte failure), the service-side trail (unary, batch, and
stream paths all append records), and the ``[audit]`` config section
(layering + drift guard)."""

import asyncio
import dataclasses
import os
import pathlib
import re
import signal
import subprocess
import sys
import time

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.audit import (
    ProofLogWriter,
    proof_record,
    read_log,
    run_audit,
    scan_records,
    verify_report_file,
)
from cpzk_tpu.audit import sign as audit_sign
from cpzk_tpu.audit.log import validate_proof_record
from cpzk_tpu.audit.pipeline import AuditState
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.server.config import AuditSettings, ServerConfig

ROOT = pathlib.Path(__file__).resolve().parent.parent


def run(coro):
    return asyncio.run(coro)


def make_log(
    path, n, users=4, reject_every=0, mismatch_every=0, rng=None
):
    """A proof log of ``n`` REAL records (same construction as the
    service's trail): returns (writer_seq, provers)."""
    rng = rng or SecureRng()
    params = Parameters.new()
    eb = Ristretto255.element_to_bytes
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng)))
        for _ in range(users)
    ]
    writer = ProofLogWriter(str(path))
    payloads = []
    for i in range(n):
        prover = provers[i % users]
        ctx = rng.fill_bytes(32)
        t = Transcript()
        t.append_context(ctx)
        wire = prover.prove_with_transcript(rng, t).to_bytes()
        verdict = True
        if reject_every and i % reject_every == 1:
            wire = wire[:-1] + bytes([wire[-1] ^ 1])
            verdict = False
        if mismatch_every and i % mismatch_every == 2:
            verdict = not verdict
        payloads.append(proof_record(
            f"u{i % users}",
            eb(prover.statement.y1), eb(prover.statement.y2),
            ctx, wire, verdict,
        ))
    writer.append_proofs(payloads)
    writer.close()
    return writer.seq, provers


# --- proof log ---------------------------------------------------------------


def test_proof_log_roundtrip_seq_resume_and_perms(tmp_path):
    path = tmp_path / "p.log"
    seq, _ = make_log(path, 5)
    assert seq == 5
    records, valid, total = read_log(str(path))
    assert [r["seq"] for r in records] == [1, 2, 3, 4, 5]
    assert valid == total
    assert all(validate_proof_record(r) is None for r in records)
    assert oct(path.stat().st_mode & 0o777) == "0o600"

    # reopening resumes the sequence, keeping the prefix contract intact
    w2 = ProofLogWriter(str(path))
    assert w2.seq == 5
    w2.append_proofs([records[0] | {}])  # payload fields reused; new seq
    w2.close()
    records2, valid2, total2 = read_log(str(path))
    assert [r["seq"] for r in records2] == [1, 2, 3, 4, 5, 6]
    assert valid2 == total2


def test_validate_proof_record_rejections():
    good = proof_record("u", b"\x01" * 32, b"\x02" * 32, b"c" * 32,
                        b"p" * 109, True)
    good["type"] = "proof"
    assert validate_proof_record(good) is None
    assert validate_proof_record({"type": "register_user"}) is not None
    for key in ("y1", "y2", "ctx", "p"):
        bad = dict(good)
        bad[key] = "zz-not-hex"
        assert validate_proof_record(bad) == f"bad-{key}"
        bad[key] = ""
        assert validate_proof_record(bad) == f"bad-{key}"
    bad = dict(good)
    bad["v"] = 2
    assert validate_proof_record(bad) == "bad-verdict"
    bad["v"] = True  # JSON booleans are not the 0/1 the service writes
    assert validate_proof_record(bad) == "bad-verdict"
    bad = dict(good)
    bad["u"] = 7
    assert validate_proof_record(bad) == "bad-user"


def test_scan_records_split_resume_equivalence(tmp_path):
    """Scanning from a cursor (offset, prev_seq) at ANY frame boundary
    yields exactly the whole-buffer scan's suffix — the property SIGKILL
    resume rests on."""
    path = tmp_path / "p.log"
    make_log(path, 9)
    buf = path.read_bytes()
    records, valid = scan_records(buf)
    assert len(records) == 9 and valid == len(buf)
    from cpzk_tpu.durability.wal import HEADER_BYTES, _HEADER

    off = 0
    for k in range(9):
        tail, tail_valid = scan_records(
            buf, offset=off, prev_seq=records[k - 1]["seq"] if k else None
        )
        assert tail == records[k:]
        assert tail_valid == valid
        length, _ = _HEADER.unpack_from(buf, off)
        off += HEADER_BYTES + length


# --- pipeline ----------------------------------------------------------------


def test_pipeline_report_totals_and_offline_signature(tmp_path):
    log = tmp_path / "p.log"
    make_log(log, 40, reject_every=10, mismatch_every=13)
    report_path = str(tmp_path / "report.json")
    report = run_audit(str(log), report_path, quantum=16)
    t = report["totals"]
    assert t["records"] == 40
    assert t["audited"] == 40
    assert t["verified"] + t["rejected"] == 40
    assert t["rejected"] == 4       # i % 10 == 1
    assert t["mismatched"] == 3     # i % 13 == 2 (and not also a reject)
    ok, reason, loaded = verify_report_file(report_path)
    assert ok, reason
    assert loaded["digest"] == report["digest"]
    # the cursor is gone after a completed run
    assert not os.path.exists(report_path + ".cursor")
    # exit-code contract: mismatches are a FINDING
    from cpzk_tpu.audit.__main__ import main as audit_main

    assert audit_main([
        "verify-report", "--report", report_path
    ]) == 0


def test_pipeline_resume_is_byte_exact(tmp_path):
    log = tmp_path / "p.log"
    make_log(log, 30, reject_every=7)
    a = str(tmp_path / "a.json")
    b = str(tmp_path / "b.json")
    key = str(tmp_path / "audit.key")
    full = run_audit(str(log), a, key_path=key, quantum=8)
    assert full is not None
    # interrupted run: 2 quanta then stop (modelling a crash after the
    # checkpoint landed), then resume to completion
    assert run_audit(str(log), b, key_path=key, quantum=8,
                     max_batches=2) is None
    assert os.path.exists(b + ".cursor")
    resumed = run_audit(str(log), b, key_path=key, quantum=8)
    assert resumed is not None
    assert open(a).read() == open(b).read()  # signature included
    assert resumed["digest"] == full["digest"]


def test_pipeline_skips_garbage_and_stops_at_corruption(tmp_path):
    from cpzk_tpu.durability.wal import encode_record

    log = tmp_path / "p.log"
    make_log(log, 6)
    # append a non-proof record (skipped) and a bad-hex proof record
    # (skipped), then a torn tail (scan stops, never raises)
    with open(log, "ab") as f:
        f.write(encode_record({"seq": 7, "type": "register_user", "u": "x"}))
        f.write(encode_record({
            "seq": 8, "type": "proof", "u": "x", "y1": "zz", "y2": "zz",
            "ctx": "00", "p": "00", "v": 1, "t": 0,
        }))
        f.write(b"\x00\x00\x00\x10CORRUPTED-TAIL")
    report = run_audit(str(log), str(tmp_path / "r.json"), quantum=4)
    t = report["totals"]
    assert t["records"] == 8
    assert t["audited"] == 6 and t["verified"] == 6
    assert t["skipped"] == 2
    assert report["log"]["valid_bytes"] < report["log"]["file_bytes"]
    ok, reason, _ = verify_report_file(str(tmp_path / "r.json"))
    assert ok, reason


def test_report_single_flipped_byte_fails_offline_verify(tmp_path):
    log = tmp_path / "p.log"
    make_log(log, 8)
    report_path = str(tmp_path / "r.json")
    run_audit(str(log), report_path, quantum=4)
    blob = bytearray(open(report_path, "rb").read())
    # flip one byte in several structurally different places
    for pos in (blob.find(b'"verified"') + 12,
                blob.find(b'"digest"') + 12,
                blob.find(b'"public_key"') + 16):
        tampered = bytearray(blob)
        tampered[pos] = tampered[pos] ^ 0x01 or 0x31
        bad_path = str(tmp_path / "bad.json")
        open(bad_path, "wb").write(bytes(tampered))
        ok, reason, _ = verify_report_file(bad_path)
        assert not ok, f"tamper at {pos} went unnoticed"


def test_wrong_log_for_cursor_refused(tmp_path):
    log1, log2 = tmp_path / "one.log", tmp_path / "two.log"
    make_log(log1, 12)
    make_log(log2, 12)
    report = str(tmp_path / "r.json")
    assert run_audit(str(log1), report, quantum=4, max_batches=1) is None
    with pytest.raises(ValueError, match="cursor belongs to"):
        run_audit(str(log2), report, quantum=4)


# --- signatures --------------------------------------------------------------


def test_schnorr_sign_verify_roundtrip(tmp_path):
    key = audit_sign.generate_key()
    pub = audit_sign.public_key(key)
    msg = b"the audit transcript digest"
    r, s = audit_sign.sign(key, msg)
    assert audit_sign.verify(pub, msg, r, s)
    assert not audit_sign.verify(pub, b"another message", r, s)
    other = audit_sign.generate_key()
    assert not audit_sign.verify(audit_sign.public_key(other), msg, r, s)
    # deterministic: same (key, message) -> same signature bytes
    assert audit_sign.sign(key, msg) == (r, s)
    # malformed inputs answer False, never raise
    assert not audit_sign.verify(b"\x00" * 32, msg, r, s)
    assert not audit_sign.verify(pub, msg, b"junk", s)
    assert not audit_sign.verify(pub, msg, r, b"short")


def test_key_file_minted_0600_and_reloaded(tmp_path):
    path = tmp_path / "audit.key"
    k1 = audit_sign.load_or_create_key(str(path))
    assert oct(path.stat().st_mode & 0o777) == "0o600"
    k2 = audit_sign.load_or_create_key(str(path))
    assert k1 == k2
    path.write_text("not hex")
    with pytest.raises(ValueError, match="not hex"):
        audit_sign.load_or_create_key(str(path))


# --- fold-state invariants ---------------------------------------------------


def test_audit_state_cursor_roundtrip(tmp_path):
    st = AuditState()
    st.note({"seq": 1, "type": "proof"}, b"V")
    st.note({"seq": 2, "type": "proof"}, b"R", mismatch=True)
    st.note({"seq": 3, "type": "junk"}, b"S")
    st.offset = 123
    cur = st.to_cursor("/var/log/proofs.log")
    back = AuditState.from_cursor(cur, "/elsewhere/proofs.log")
    assert back.chain == st.chain
    assert back.records == 3 and back.audited == 2
    assert back.mismatched == 1 and back.skipped == 1
    assert back.prev_seq == 3 and back.first_seq == 1
    with pytest.raises(ValueError, match="cursor belongs to"):
        AuditState.from_cursor(cur, "/var/log/other.log")


# --- service-side trail ------------------------------------------------------


def test_service_appends_records_on_all_verify_paths(tmp_path):
    """Unary VerifyProof, VerifyProofBatch, and VerifyProofStream all
    append (statement, challenge, proof, verdict) records; the bulk
    pipeline then re-verifies the trail to an all-clean report."""
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.protocol.batch import CpuBackend
    from cpzk_tpu.server import RateLimiter, ServerState
    from cpzk_tpu.server.batching import DynamicBatcher
    from cpzk_tpu.server.service import serve

    log_path = tmp_path / "service.log"

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        provers = [
            Prover(params, Witness(Ristretto255.random_scalar(rng)))
            for _ in range(6)
        ]
        eb = Ristretto255.element_to_bytes
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=1.0)
        audit_log = ProofLogWriter(str(log_path))
        server, port = await serve(
            ServerState(), RateLimiter(10**9, 10**9), port=0,
            backend=backend, batcher=batcher, audit_log=audit_log,
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                for i, p in enumerate(provers):
                    r = await client.register(
                        f"u{i}", eb(p.statement.y1), eb(p.statement.y2))
                    assert r.success

                async def login_args(i):
                    ch = await client.create_challenge(f"u{i}")
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    return cid, provers[i].prove_with_transcript(
                        rng, t).to_bytes()

                # unary (1 record)
                cid, wire = await login_args(0)
                assert (await client.verify_proof("u0", cid, wire)).success
                # unary failure (1 record, verdict 0) — bad proof byte
                cid, wire = await login_args(1)
                bad = wire[:-1] + bytes([wire[-1] ^ 1])
                import grpc

                with pytest.raises(grpc.aio.AioRpcError):
                    await client.verify_proof("u1", cid, bad)
                # batch (2 records)
                pairs = [await login_args(i) for i in (2, 3)]
                resp = await client.verify_proof_batch(
                    ["u2", "u3"], [p[0] for p in pairs],
                    [p[1] for p in pairs])
                assert all(r.success for r in resp.results)
                # stream (2 records)
                entries = []
                for i in (4, 5):
                    cid, wire = await login_args(i)
                    entries.append((f"u{i}", cid, wire))
                oks = [
                    v.ok async for v in client.verify_proof_stream(entries)
                ]
                assert oks == [True, True]
        finally:
            await batcher.stop()
            audit_log.close()
            await server.stop(None)

    run(main())
    records, valid, total = read_log(str(log_path))
    assert len(records) == 6
    assert sum(r["v"] for r in records) == 5
    # the trail replays clean: recorded verdicts match re-verification
    report = run_audit(
        str(log_path), str(log_path) + ".report.json", quantum=4)
    assert report["totals"]["mismatched"] == 0
    assert report["totals"]["verified"] == 5
    assert report["totals"]["rejected"] == 1


# --- SIGKILL resume (real process) ------------------------------------------


@pytest.mark.slow
def test_pipeline_sigkill_resume_byte_exact(tmp_path):
    """Kill -9 the pipeline mid-run; the rerun's signed report is
    byte-identical to an uninterrupted run (CI audit-smoke twin)."""
    log = tmp_path / "p.log"
    make_log(log, 400, reject_every=11)
    key = str(tmp_path / "k.key")
    ref = str(tmp_path / "ref.json")
    assert run_audit(str(log), ref, key_path=key, quantum=50) is not None

    out = str(tmp_path / "killed.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "cpzk_tpu.audit", "run",
         "--log", str(log), "--report", out, "--key", key,
         "--quantum", "50", "--quiet"],
        cwd=str(ROOT), env=env,
    )
    # wait for the first checkpoint, then SIGKILL mid-run
    deadline = time.monotonic() + 60
    cursor = out + ".cursor"
    while time.monotonic() < deadline and proc.poll() is None:
        if os.path.exists(cursor):
            break
        time.sleep(0.02)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    # resume (fresh process) and compare byte-for-byte
    done = subprocess.run(
        [sys.executable, "-m", "cpzk_tpu.audit", "run",
         "--log", str(log), "--report", out, "--key", key,
         "--quantum", "50", "--quiet"],
        cwd=str(ROOT), env=env, capture_output=True, timeout=180,
    )
    assert done.returncode == 0, done.stderr
    assert open(out).read() == open(ref).read()


# --- config ------------------------------------------------------------------


def test_audit_config_layering_and_validation(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = ServerConfig.from_env()
    assert cfg.audit.enabled is False
    assert cfg.audit.fsync == "off"

    (tmp_path / "server.toml").write_text(
        '[audit]\nenabled = true\nlog_path = "proofs.log"\n'
        'fsync = "interval"\n'
    )
    monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
    cfg = ServerConfig.from_env()
    assert cfg.audit.enabled is True
    assert cfg.audit.log_path == "proofs.log"
    assert cfg.audit.fsync == "interval"
    cfg.validate()
    monkeypatch.setenv("SERVER_AUDIT_FSYNC", "ALWAYS")
    monkeypatch.setenv("SERVER_AUDIT_FSYNC_INTERVAL_MS", "77")
    monkeypatch.setenv("SERVER_AUDIT_LOG_PATH", "/tmp/other.log")
    cfg = ServerConfig.from_env()
    assert cfg.audit.fsync == "always"
    assert cfg.audit.fsync_interval_ms == 77.0
    assert cfg.audit.log_path == "/tmp/other.log"

    bad = ServerConfig()
    bad.audit.enabled = True  # without a log_path
    with pytest.raises(ValueError, match="requires log_path"):
        bad.validate()
    bad = ServerConfig()
    bad.audit.fsync = "sometimes"
    with pytest.raises(ValueError, match="audit.fsync"):
        bad.validate()
    bad = ServerConfig()
    bad.audit.fsync_interval_ms = 0
    with pytest.raises(ValueError, match="fsync_interval_ms"):
        bad.validate()
    # stream knobs ride [tpu]
    bad = ServerConfig()
    bad.tpu.stream_window = 0
    with pytest.raises(ValueError, match="stream_window"):
        bad.validate()
    bad = ServerConfig()
    bad.tpu.stream_entry_deadline_ms = -1
    with pytest.raises(ValueError, match="stream_entry_deadline_ms"):
        bad.validate()


def test_audit_config_keys_documented():
    """CI drift guard (pattern from test_durability.py): every [audit]
    knob ships in the TOML example, the .env example, and the
    operations-doc knob inventory."""
    keys = [f.name for f in dataclasses.fields(AuditSettings)]
    assert keys

    toml_text = (ROOT / "config" / "server.toml.example").read_text()
    m = re.search(r"^\[audit\]$", toml_text, re.M)
    assert m, "[audit] section missing from config/server.toml.example"
    section = toml_text[m.end():].split("\n[", 1)[0]
    env_text = (ROOT / ".env.example").read_text()
    docs = (ROOT / "docs" / "operations.md").read_text()
    for key in keys:
        assert re.search(rf"^{key}\s*=", section, re.M), (
            f"[audit] key {key!r} missing from config/server.toml.example"
        )
        assert f"SERVER_AUDIT_{key.upper()}" in env_text, (
            f"SERVER_AUDIT_{key.upper()} missing from .env.example"
        )
        assert f"`audit.{key}`" in docs, (
            f"`audit.{key}` missing from the docs/operations.md "
            "knob inventory"
        )
    # the streaming knobs live in [tpu]; guard them too
    for key in ("stream_window", "stream_entry_deadline_ms"):
        assert f"`tpu.{key}`" in docs, (
            f"`tpu.{key}` missing from the docs/operations.md knob "
            "inventory"
        )


def test_cli_generate_run_verify(tmp_path, monkeypatch):
    """The CLI surface end to end in-process: generate -> run -> tamper
    -> verify-report exit codes."""
    from cpzk_tpu.audit.__main__ import main as audit_main

    log = str(tmp_path / "g.log")
    rc = audit_main(["generate", "--n", "30", "--out", log,
                     "--users", "3", "--reject-frac", "0.2"])
    assert rc == 0
    report = str(tmp_path / "g.json")
    rc = audit_main(["run", "--log", log, "--report", report,
                     "--quantum", "8", "--quiet"])
    assert rc == 0  # rejects recorded as rejects are not mismatches
    assert audit_main(["verify-report", "--report", report]) == 0
    blob = open(report).read().replace('"mismatched":0', '"mismatched":1')
    open(report, "w").write(blob)
    assert audit_main(["verify-report", "--report", report]) == 1
