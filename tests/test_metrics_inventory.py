"""CI drift guards for the telemetry surface (tier-1).

1. The metrics namespace has no kind collisions: one name is only ever a
   counter OR a gauge OR a histogram (a collision would blow up the
   Prometheus exposition with a duplicated timeseries).
2. Every metric name used in ``cpzk_tpu/`` appears in the documented
   inventory in ``docs/operations.md`` — new instrumentation cannot ship
   undocumented, and stale docs rows are caught by inspection.
3. The whole metrics facade works with ``prometheus_client`` absent
   (subprocess with the import blocked), exercising the no-op backing's
   counters, labeled children, histogram count/sum, and reads.
"""

import os
import pathlib
import re
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

#: metric-creation calls with a literal name argument
_LITERAL_CALL = re.compile(
    r"""(?:metrics\.)?\b(counter|histogram|gauge)\(\s*['"]([a-z0-9._]+)['"]"""
)

#: names built dynamically (f-strings / dict lookups) that the regex scan
#: cannot see, with their kinds: the per-RPC families from the traced_rpc
#: decorator and the stage histograms fed by BatchStages.
_KIND_C, _KIND_H = "c", "h"
_RPC_PREFIXES = (
    "auth.register",
    "auth.register_batch",
    "auth.challenge",
    "auth.verify",
    "auth.verify_batch",
    "auth.verify_stream",
)
DYNAMIC_NAMES: dict[str, str] = {}
for _prefix in _RPC_PREFIXES:
    DYNAMIC_NAMES[f"{_prefix}.requests"] = _KIND_C
    DYNAMIC_NAMES[f"{_prefix}.success"] = _KIND_C
    DYNAMIC_NAMES[f"{_prefix}.failure"] = _KIND_C
    DYNAMIC_NAMES[f"{_prefix}.duration"] = _KIND_H
DYNAMIC_NAMES["tpu.batch.host_time"] = _KIND_H
DYNAMIC_NAMES["tpu.batch.device_time"] = _KIND_H


def _collect_literal_names() -> dict[str, set[str]]:
    kinds_by_name: dict[str, set[str]] = {}
    for path in (ROOT / "cpzk_tpu").rglob("*.py"):
        if path.name == "metrics.py":  # the facade itself, not a user
            continue
        for kind, name in _LITERAL_CALL.findall(path.read_text()):
            kinds_by_name.setdefault(name, set()).add(kind[0])
    return kinds_by_name


def test_metric_registry_has_no_kind_collisions():
    kinds_by_name = _collect_literal_names()
    for name, kind in DYNAMIC_NAMES.items():
        kinds_by_name.setdefault(name, set()).add(kind)
    collisions = {
        name: kinds for name, kinds in kinds_by_name.items() if len(kinds) > 1
    }
    assert not collisions, (
        f"metric names used with conflicting kinds: {collisions}"
    )
    # sanity: the scan actually found the serving-plane metrics
    assert "tpu.queue.depth" in kinds_by_name
    assert "tpu.batch.queue_wait" in kinds_by_name
    assert "rpc.requests" in kinds_by_name


def test_every_metric_name_is_documented():
    docs = (ROOT / "docs" / "operations.md").read_text()
    kinds_by_name = _collect_literal_names()
    used = set(kinds_by_name) | set(DYNAMIC_NAMES)
    undocumented = sorted(
        name for name in used if f"`{name}`" not in docs
    )
    assert not undocumented, (
        "metric names used in cpzk_tpu/ but missing from the "
        f"docs/operations.md telemetry inventory: {undocumented}"
    )


_NOOP_SCRIPT = """
import importlib.abc, sys

class _BlockPrometheus(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path, target=None):
        if fullname.split(".")[0] == "prometheus_client":
            raise ImportError("prometheus_client blocked for no-op test")
        return None

sys.meta_path.insert(0, _BlockPrometheus())

from cpzk_tpu.server import metrics

assert metrics.HAVE_PROMETHEUS is False

c = metrics.counter("noop.test.count")
c.inc()
c.inc(2)
assert metrics.read("noop.test.count") == 3.0

h = metrics.histogram("noop.test.duration")
h.observe(0.25)
h.observe(0.75)
assert metrics.read_histogram("noop.test.duration") == (2.0, 1.0)
assert metrics.read("noop.test.duration", "h") == 1.0

g = metrics.gauge("noop.test.depth")
g.set(7)
assert metrics.read("noop.test.depth", "g") == 7.0

fam = metrics.counter("noop.test.labeled", labelnames=("rpc",))
fam.labels(rpc="X").inc()
assert metrics.read("noop.test.labeled", labels={"rpc": "X"}) == 1.0
assert metrics.read("noop.test.labeled", labels={"rpc": "Y"}) == 0.0

assert metrics.start_exporter("127.0.0.1", 0) is False
assert ("c", "noop.test.count") in metrics.registered()

# flight-recorder metric families work on the no-op backing too: the
# labeled jit cache/compile counters, dispatch-gap histogram, busy
# fraction + occupancy + throughput gauges
from cpzk_tpu.observability import flightrec

flightrec.note_jit("combined/1024", True)
flightrec.note_jit("combined/1024", False)
assert metrics.read("tpu.jit.cache", labels={"outcome": "miss"}) == 1.0
assert metrics.read("tpu.jit.cache", labels={"outcome": "hit"}) == 1.0
assert metrics.read("tpu.jit.compiles", labels={"shape": "combined/1024"}) == 1.0

rec = flightrec.get_flight_recorder()
rec.note_device_interval(1.0, 1.5)
rec.note_device_interval(2.0, 2.25)
assert metrics.read_histogram("tpu.dispatch.gap") == (2.0, 0.5)
assert metrics.read("tpu.device.busy_fraction", "g") > 0.0

rec.record(flightrec.FlightRecord(batch=8, lanes=16, occupancy=0.5))
assert metrics.read("tpu.batch.occupancy", "g") == 0.5
assert metrics.read("tpu.throughput.proofs_per_s", "g") >= 0.0

# the ops plane's text exposition works on the no-op backing too, with
# the identical family set the prometheus backing would render
text = metrics.render_exposition()
for _kind, name in metrics.registered():
    assert metrics._sanitize(name) in text, name
assert "noop_test_count_total 3.0" in text
assert 'noop_test_labeled_total{rpc="X"} 1.0' in text
assert text.rstrip().endswith("# EOF")
print("NOOP-OK")
"""


def test_metrics_facade_without_prometheus_client():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    result = subprocess.run(
        [sys.executable, "-c", _NOOP_SCRIPT],
        capture_output=True,
        text=True,
        cwd=str(ROOT),
        env=env,
        timeout=120,
    )
    assert result.returncode == 0, result.stderr
    assert "NOOP-OK" in result.stdout
