"""Differential tests: JAX curve kernels vs the integer-exact host edwards
module. All device functions are jitted once at module scope (eager limb
arithmetic dispatches thousands of tiny ops)."""

import secrets

import numpy as np
import pytest

import jax

from cpzk_tpu.core import edwards as he
from cpzk_tpu.core import scalars as hs
from cpzk_tpu.ops import curve, limbs

N = 16

j_add = jax.jit(curve.add)
j_double = jax.jit(curve.double)
j_eq = jax.jit(curve.eq)
j_is_identity = jax.jit(curve.is_identity)
j_scalar_mul = jax.jit(curve.scalar_mul)
j_tree_sum = jax.jit(curve.tree_sum)
j_decode = jax.jit(curve.decode)
j_encode = jax.jit(curve.encode)


def rand_points(n: int) -> list[he.Point]:
    pts = []
    for _ in range(n - 2):
        k = secrets.randbelow(hs.L)
        pts.append(he.pt_scalar_mul(he.BASEPOINT, k))
    pts.append(he.IDENTITY)
    pts.append(he.BASEPOINT)
    return pts


@pytest.fixture(scope="module")
def pts():
    p = rand_points(N)
    q = rand_points(N)
    return p, q, curve.points_to_device(p), curve.points_to_device(q)


def assert_points_equal(host_pts: list[he.Point], dev_pt) -> None:
    got = curve.points_from_device(jax.device_get(dev_pt))
    for hp, gp in zip(host_pts, got):
        assert he.pt_eq(hp, tuple(v % he.P for v in gp))


def test_add_double(pts):
    p, q, dp, dq = pts
    assert_points_equal([he.pt_add(a, b) for a, b in zip(p, q)], j_add(dp, dq))
    assert_points_equal([he.pt_double(a) for a in p], j_double(dp))


def test_eq_identity(pts):
    p, q, dp, dq = pts
    assert list(np.asarray(j_eq(dp, dp))) == [True] * N
    expected = [he.pt_eq(a, b) for a, b in zip(p, q)]
    assert list(np.asarray(j_eq(dp, dq))) == expected
    assert list(np.asarray(j_is_identity(dp))) == [he.pt_is_identity(a) for a in p]


def test_scalar_mul(pts):
    p, _, dp, _ = pts
    ks = [secrets.randbelow(hs.L) for _ in range(N - 2)] + [0, 1]
    win = curve.scalars_to_windows(ks)
    expected = [he.pt_scalar_mul(a, k) for a, k in zip(p, ks)]
    assert_points_equal(expected, j_scalar_mul(dp, win))


def test_tree_sum(pts):
    p, _, dp, _ = pts
    acc = he.IDENTITY
    for a in p:
        acc = he.pt_add(acc, a)
    assert_points_equal([acc], tuple(c[:, None] for c in j_tree_sum(dp)))

    # non-power-of-two length
    p3 = p[:3]
    dp3 = tuple(c[:, :3] for c in dp)
    acc3 = he.pt_add(he.pt_add(p3[0], p3[1]), p3[2])
    assert_points_equal([acc3], tuple(c[:, None] for c in j_tree_sum(dp3)))


def test_encode_decode_roundtrip(pts):
    p, _, dp, _ = pts
    wire_host = [he.ristretto_encode(a) for a in p]
    enc = np.asarray(j_encode(dp)).astype(np.uint8)  # [32, n]
    assert [bytes(enc[:, j].tobytes()) for j in range(N)] == wire_host

    dec, valid = j_decode(jax.numpy.asarray(enc))
    assert list(np.asarray(valid)) == [True] * N
    assert_points_equal([he.ristretto_decode(w) for w in wire_host], dec)


def test_decode_rejects_invalid():
    cases = []
    # non-canonical: p + 1 (encodes as even, >= p)
    cases.append(((he.P + 1) % 2**256).to_bytes(32, "little"))
    # negative (odd) s
    cases.append((3).to_bytes(32, "little"))
    # s with high bit garbage: all 0xFF
    cases.append(b"\xff" * 32)
    # valid encodings for control
    cases.append(he.ristretto_encode(he.BASEPOINT))
    # not on curve: s=2 -> check host
    cases.append((2).to_bytes(32, "little"))
    arr = np.frombuffer(b"".join(cases), dtype=np.uint8).reshape(len(cases), 32)
    _, valid = j_decode(jax.numpy.asarray(arr.T))
    expected = [he.ristretto_decode(c) is not None for c in cases]
    assert list(np.asarray(valid)) == expected
