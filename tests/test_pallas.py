"""Differential tests for the opt-in pallas point kernels (interpret mode
on the CPU backend): same inputs, bit-identical outputs vs the XLA path.
"""

import secrets

import numpy as np
import pytest

from cpzk_tpu.core import edwards as he
from cpzk_tpu.core import scalars as hs
from cpzk_tpu.ops import curve, pallas_kernels

N = 128  # minimum pallas lane width


@pytest.fixture(scope="module")
def pts():
    host = [he.pt_scalar_mul(he.BASEPOINT, secrets.randbelow(hs.L)) for _ in range(N - 1)]
    host.append(he.IDENTITY)
    return host, curve.points_to_device(host)


def canon(dev_pt):
    return [
        tuple(v % he.P for v in p)
        for p in curve.points_from_device([np.asarray(c) for c in dev_pt])
    ]


def test_pallas_add_matches_xla(pts):
    host, dp = pts
    dq = tuple(np.roll(np.asarray(c), 7, axis=1) for c in dp)
    xla = curve.add(dp, dq)
    pal = pallas_kernels.point_add(dp, dq)
    for a, b in zip(canon(xla), canon(pal)):
        assert he.pt_eq(a, b)
    # and both match the host oracle
    host_q = host[-7:] + host[:-7]
    for got, (p, q) in zip(canon(pal), zip(host, host_q)):
        assert he.pt_eq(got, he.pt_add(p, q))


def test_pallas_double_matches_xla(pts):
    host, dp = pts
    pal = pallas_kernels.point_double(dp)
    for got, p in zip(canon(pal), host):
        assert he.pt_eq(got, he.pt_double(p))


def test_supported_predicate(pts):
    _, dp = pts
    assert pallas_kernels.supported(dp)
    small = tuple(c[:, :4] for c in dp)
    assert not pallas_kernels.supported(small)  # < 128 lanes -> XLA path


def test_pallas_double_k_matches_xla(pts):
    """The fused k-doubling kernel is bit-exact vs k host doublings
    (interpret mode off-TPU)."""
    host, dp = pts
    pal = pallas_kernels.point_double_k(dp, 4)
    for got, p in zip(canon(pal), host):
        exp = p
        for _ in range(4):
            exp = he.pt_double(exp)
        assert he.pt_eq(got, exp)
