"""Differential tests: TpuBackend (JAX device plane) vs CpuBackend (host
oracle) through the full BatchVerifier protocol path — accept/reject must be
bit-identical (SURVEY.md §4 tier for the TPU build)."""

import pytest

from cpzk_tpu import (
    BatchVerifier,
    Parameters,
    Prover,
    SecureRng,
    Statement,
    Transcript,
    Witness,
)
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.ops.backend import TpuBackend
from cpzk_tpu.protocol.batch import CpuBackend


@pytest.fixture(scope="module")
def backend():
    return TpuBackend()


def make_entries(n: int, context: bytes | None = None, params: Parameters | None = None):
    rng = SecureRng()
    params = params or Parameters.new()
    entries = []
    for _ in range(n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        transcript = Transcript()
        if context is not None:
            transcript.append_context(context)
        proof = prover.prove_with_transcript(rng, transcript)
        entries.append((params, prover.statement, proof))
    return entries


def test_combined_accepts_valid_batch(backend):
    entries = make_entries(5)
    bv = BatchVerifier(backend=backend)
    for params, statement, proof in entries:
        bv.add(params, statement, proof)
    assert bv.verify(SecureRng()) == [None] * 5


def test_mixed_batch_matches_cpu_oracle(backend):
    entries = make_entries(6)
    rng = SecureRng()
    # corrupt entry 2: swap in a statement from a different witness
    params = entries[2][0]
    wrong = Statement.from_witness(params, Witness(Ristretto255.random_scalar(rng)))
    entries[2] = (params, wrong, entries[2][2])

    results = {}
    for name, be in (("tpu", backend), ("cpu", CpuBackend())):
        bv = BatchVerifier(backend=be)
        for p, st, pr in entries:
            bv.add(p, st, pr)
        results[name] = [e is None for e in bv.verify(SecureRng())]

    assert results["tpu"] == results["cpu"] == [True, True, False, True, True, True]


def test_context_bound_batch(backend):
    entries = make_entries(4, context=b"batch-ctx")
    bv = BatchVerifier(backend=backend)
    for p, st, pr in entries:
        bv.add_with_context(p, st, pr, b"batch-ctx")
    assert bv.verify(SecureRng()) == [None] * 4

    # wrong context -> every proof rejected, same as CPU oracle
    bv2 = BatchVerifier(backend=backend)
    for p, st, pr in entries:
        bv2.add_with_context(p, st, pr, b"other-ctx")
    assert all(e is not None for e in bv2.verify(SecureRng()))


def test_custom_generators_batch(backend):
    rng = SecureRng()
    g = Ristretto255.scalar_mul(Ristretto255.generator_g(), Ristretto255.random_scalar(rng))
    h = Ristretto255.scalar_mul(Ristretto255.generator_h(), Ristretto255.random_scalar(rng))
    params = Parameters.with_generators(g, h)
    entries = make_entries(3, params=params)
    bv = BatchVerifier(backend=backend)
    for p, st, pr in entries:
        bv.add(p, st, pr)
    assert bv.verify(SecureRng()) == [None] * 3
