"""Multi-chip serving plane tests (ISSUE 12): deadline-aware placement
over per-device dispatch lanes, per-lane breaker isolation (one sick
chip degrades only its lane), the ``lanes = 1`` structural fast path,
drain-then-join shutdown across all lanes, the big-batch mesh path, the
audit pipeline's router fan-out (digest byte-identical to single-lane),
per-device AOT prewarm cache keys, the mesh-devices validation fix, and
the ``[tpu] lanes`` / ``mesh_threshold`` knob plumbing + drift guard.
"""

import asyncio
import json
import pathlib
import re
import time

import pytest

from cpzk_tpu.observability import get_flight_recorder
from cpzk_tpu.protocol.batch import CpuBackend, VerifierBackend
from cpzk_tpu.server.batching import DynamicBatcher
from cpzk_tpu.server.dispatch import LaneStopped
from cpzk_tpu.server.router import LaneRouter

from test_dispatch_lane import ExplodingBackend, RecordingBackend, make_entries

ROOT = pathlib.Path(__file__).resolve().parent.parent


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _fresh_recorder():
    rec = get_flight_recorder()
    rec.clear()
    yield
    rec.clear()


class SlowBackend(VerifierBackend):
    """CPU oracle with a fixed per-call delay (a slow chip)."""

    prefers_combined = False

    def __init__(self, delay_s: float = 0.0):
        self.calls = 0
        self.delay_s = delay_s
        self._inner = CpuBackend()

    def verify_combined(self, rows, beta):  # pragma: no cover - unused
        raise AssertionError("prefers_combined is False")

    def verify_each(self, rows):
        self.calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        return self._inner.verify_each(rows)


# --- placement ---------------------------------------------------------------


def test_placement_prefers_least_backlogged_lane():
    """Skewed lane depths: a new batch lands on the lane with the
    shortest predicted completion (least pending entries at equal drain
    rates)."""
    router = LaneRouter([CpuBackend(), CpuBackend(), CpuBackend()])
    for slot, pending in zip(router._slots, (500, 3, 900), strict=True):
        slot.pending = pending
        slot.drain_rate = 100.0
    slot, probe = router._pick(4)
    assert not probe
    assert slot is router._slots[1]


def test_placement_is_drain_rate_aware():
    """Equal depths, unequal drain rates: the faster lane wins — depth
    alone would tie, but predicted completion = depth / drain rate."""
    router = LaneRouter([CpuBackend(), CpuBackend()])
    for slot, rate in zip(router._slots, (10.0, 1000.0), strict=True):
        slot.pending = 200
        slot.drain_rate = rate
    for _ in range(4):  # stable across the rotating tie-break
        slot, _ = router._pick(4)
        assert slot is router._slots[1]


def test_placement_spreads_cold_lanes():
    """Cold start (no drain history anywhere): the rotating tie-break
    spreads batches instead of piling them on lane 0."""
    router = LaneRouter([CpuBackend() for _ in range(4)])
    picked = set()
    for _ in range(8):
        slot, _ = router._pick(1)
        slot.pending += 1  # as submit() would
        picked.add(slot.label)
    assert len(picked) >= 3, picked


def test_router_serves_across_all_lanes():
    """Sustained load through the batcher lands dispatches on every lane
    (the acceptance shape: per-lane dispatch counters all nonzero), and
    every flight record carries its lane index."""
    router = LaneRouter([CpuBackend() for _ in range(3)])

    async def main():
        batcher = DynamicBatcher(
            CpuBackend(), max_batch=4, window_ms=1.0, max_queue=10_000,
            router=router,
        )
        batcher.start()
        waves = [make_entries(4) for _ in range(9)]
        results = await asyncio.gather(
            *[batcher.submit_many(w) for w in waves]
        )
        status = router.status()
        await batcher.stop()
        return results, status

    results, status = run(main())
    assert all(r == [None] * 4 for r in results)
    assert [row["dispatches"] > 0 for row in status["lanes"]] == [True] * 3
    assert sum(row["dispatches"] for row in status["lanes"]) == 9
    lanes_seen = {rec.lane for rec in get_flight_recorder().snapshot()}
    assert lanes_seen == {0, 1, 2}


# --- per-lane breaker --------------------------------------------------------


def test_sick_lane_degrades_only_itself_and_readmits():
    """Per-lane breaker isolation: a raising backend in lane 2 errors
    only the batches placed on it before its breaker opens; lanes 0/1/3
    keep settling with zero errors; after the cooldown the next batch
    probes lane 2 and (backend healed) re-admits it."""
    sick = ExplodingBackend(explode_times=1)  # heals after one raise
    backends = [CpuBackend(), CpuBackend(), sick, CpuBackend()]
    router = LaneRouter(backends, recovery_after_s=0.05)

    async def main():
        router.start()
        errors = 0
        # drive until lane 2 has taken (and failed) its batch
        for _ in range(12):
            try:
                res = await router.submit(make_entries(2), None)
                assert res == [None, None]
            except RuntimeError:
                errors += 1
            if errors:
                break
        assert errors == 1, "lane 2 never drew a batch"
        assert router.status()["lanes"][2]["breaker"] == "open"
        # while OPEN, lane 2 is skipped: everything settles cleanly
        for _ in range(8):
            assert await router.submit(make_entries(2), None) == [None, None]
        assert sick.calls == 1  # no traffic reached the sick chip
        healthy_errors = [
            router.status()["lanes"][i]["errors"] for i in (0, 1, 3)
        ]
        assert healthy_errors == [0, 0, 0]
        # past the cooldown the next batch is the probe; backend healed,
        # so the lane re-admits
        await asyncio.sleep(0.06)
        for _ in range(8):
            assert await router.submit(make_entries(2), None) == [None, None]
            if router.status()["lanes"][2]["breaker"] == "closed":
                break
        status = router.status()["lanes"][2]
        assert status["breaker"] == "closed"
        assert status["probes"] == 1
        assert sick.calls >= 2  # the probe ran on the sick lane
        await router.stop()

    run(main())


def test_all_lanes_open_still_routes():
    """Every breaker OPEN is not a dead server: the router places the
    batch anyway (least-loaded) rather than refusing all work."""
    sick = ExplodingBackend()  # never heals
    router = LaneRouter([sick], recovery_after_s=1000.0)

    async def main():
        router.start()
        with pytest.raises(RuntimeError):
            await router.submit(make_entries(2), None)
        assert router.status()["lanes"][0]["breaker"] == "open"
        with pytest.raises(RuntimeError):  # routed anyway, still sick
            await router.submit(make_entries(2), None)
        await router.stop()

    run(main())


# --- lanes = 1 structural fast path ------------------------------------------


def test_single_lane_config_never_constructs_a_router(monkeypatch, tmp_path):
    """``[tpu] lanes = 1`` (the default) must keep the single-lane path
    structurally unchanged: ``build_backend`` never constructs a
    LaneRouter (spy raises), the batcher has no router, and batches
    verify exactly as before."""
    from cpzk_tpu.server import router as router_mod
    from cpzk_tpu.server.__main__ import build_backend
    from cpzk_tpu.server.config import ServerConfig

    def boom(*a, **k):  # noqa: ARG001
        raise AssertionError("LaneRouter constructed on the lanes=1 path")

    monkeypatch.setattr(router_mod.LaneRouter, "__init__", boom)
    cfg = ServerConfig()
    cfg.tpu.backend = "tpu"
    cfg.tpu.lanes = 1
    backend, batcher = build_backend(cfg)
    assert batcher is not None and batcher.router is None

    async def main():
        batcher.start()
        results = await batcher.submit_many(make_entries(2))
        await batcher.stop()
        return results

    assert run(main()) == [None, None]
    # single-lane flight records carry no lane index (nothing changed)
    assert {r.lane for r in get_flight_recorder().snapshot()} == {None}


# --- shutdown ----------------------------------------------------------------


def test_router_stop_resolves_every_future_across_lanes():
    """Drain-then-join fanned over N lanes: stop() resolves every
    accepted future exactly once with real results, refuses new work."""
    backends = [SlowBackend(delay_s=0.03) for _ in range(3)]
    router = LaneRouter(backends)

    async def main():
        router.start()
        futs = [router.submit(make_entries(2), None) for _ in range(6)]
        stop_task = asyncio.ensure_future(router.stop())
        await asyncio.sleep(0)
        with pytest.raises(LaneStopped):
            router.submit(make_entries(1), None)
        await stop_task
        assert all(f.done() for f in futs), "stop() returned before drain"
        return await asyncio.gather(*futs)

    results = run(main())
    assert results == [[None, None]] * 6
    assert sum(b.calls for b in backends) == 6


# --- mesh path ---------------------------------------------------------------


def test_mesh_threshold_routes_big_batches_to_the_mesh_lane():
    """Batches at/above ``mesh_threshold`` take the mesh lane (one
    sharded program); smaller ones stay on the per-device lanes."""
    mesh = RecordingBackend()
    lanes = [RecordingBackend(), RecordingBackend()]
    router = LaneRouter(lanes, mesh_backend=mesh, mesh_threshold=8)

    async def main():
        router.start()
        big = await router.submit(make_entries(8), None)
        small = await router.submit(make_entries(2), None)
        status = router.status()
        await router.stop()
        return big, small, status

    big, small, status = run(main())
    assert big == [None] * 8 and small == [None] * 2
    assert mesh.sizes == [8]
    assert sum(len(b.sizes) for b in lanes) == 1
    assert status["mesh"]["dispatches"] == 1
    assert status["mesh_threshold"] == 8
    lanes_seen = {rec.lane for rec in get_flight_recorder().snapshot()}
    assert lanes_seen == set()  # router.submit(None stages): no records


def test_mesh_lane_breaker_falls_back_to_per_device_lanes():
    """A mesh blow-up opens the mesh breaker: the next big batch routes
    per-device instead of dying on the mesh again."""
    mesh = ExplodingBackend()  # never heals
    lanes = [RecordingBackend(), RecordingBackend()]
    router = LaneRouter(
        lanes, mesh_backend=mesh, mesh_threshold=4, recovery_after_s=1000.0,
    )

    async def main():
        router.start()
        with pytest.raises(RuntimeError):
            await router.submit(make_entries(4), None)
        ok = await router.submit(make_entries(4), None)
        status = router.status()
        await router.stop()
        return ok, status

    ok, status = run(main())
    assert ok == [None] * 4
    assert status["mesh"]["breaker"] == "open"
    assert sum(len(b.sizes) for b in lanes) == 1


# --- audit through the router ------------------------------------------------


def test_audit_router_digest_identical_to_single_lane(tmp_path):
    """The audit pipeline replaying through the LaneRouter (each quantum
    fanned across lanes) produces a BYTE-identical signed report to the
    single-engine replay — placement never reorders the fold."""
    from cpzk_tpu.audit.__main__ import main as audit_main
    from cpzk_tpu.audit.pipeline import run_audit

    log = str(tmp_path / "p.log")
    rc = audit_main(["generate", "--n", "60", "--out", log,
                     "--users", "4", "--reject-frac", "0.1",
                     "--mismatch-frac", "0.05"])
    assert rc == 0
    key = str(tmp_path / "shared.key")
    single = str(tmp_path / "single.json")
    routed = str(tmp_path / "routed.json")
    rep1 = run_audit(log, single, key_path=key, quantum=16, lanes=1)
    rep2 = run_audit(log, routed, key_path=key, quantum=16, lanes=3)
    assert rep1["totals"]["mismatched"] > 0  # the audit found the lies
    assert rep1["digest"] == rep2["digest"]
    assert rep1["totals"] == rep2["totals"]
    b1 = pathlib.Path(single).read_bytes()
    b2 = pathlib.Path(routed).read_bytes()
    assert b1 == b2, "routed replay report differs from single-lane"


def test_audit_cli_accepts_lanes(tmp_path):
    from cpzk_tpu.audit.__main__ import main as audit_main

    log = str(tmp_path / "c.log")
    assert audit_main(["generate", "--n", "12", "--out", log]) == 0
    report = str(tmp_path / "c.json")
    rc = audit_main(["run", "--log", log, "--report", report,
                     "--quantum", "5", "--lanes", "2", "--quiet"])
    assert rc == 0
    assert audit_main(["verify-report", "--report", report]) == 0


# --- per-device prewarm / AOT cache keys -------------------------------------


def test_prewarm_keys_are_device_scoped(monkeypatch):
    """The prewarm-bug fix: prewarm with an explicit device registers
    device-suffixed jit/AOT keys, a pinned backend's dispatch finds
    THEM (zero compile spans — jit HITs booked), and the unpinned
    default-device keys stay untouched (no phantom hits)."""
    import jax

    from cpzk_tpu.ops import backend as backend_mod
    from cpzk_tpu.ops.backend import TpuBackend, prewarm_executables

    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    monkeypatch.setattr(backend_mod, "_AOT_CACHE", {})
    dev = jax.local_devices()[0]
    warmed = prewarm_executables([6], devices=[dev])
    suffix = f"dev{dev.id}"
    assert set(warmed) == {f"combined/8/{suffix}", f"each/8/True/{suffix}"}
    # idempotent per (shape, device); the default device is NOT warmed
    assert prewarm_executables([6], devices=[dev]) == []
    assert all(key[-1] == suffix for key in backend_mod._AOT_CACHE)
    assert backend_mod._aot_get("combined", 8) is None  # unpinned miss

    async def main():
        # pippenger_min pinned: an earlier test may have reloaded the
        # backend module with a tiny CPZK_PIPPENGER_MIN, and the prewarm
        # plan covers the rowcombined path this test is about
        batcher = DynamicBatcher(
            TpuBackend(device=dev, pippenger_min=1 << 62),
            max_batch=16, window_ms=1.0,
        )
        batcher.start()
        results = await batcher.submit_many(make_entries(6))
        await batcher.stop()
        return results

    assert run(main()) == [None] * 6
    rec = get_flight_recorder().snapshot()[-1]
    assert rec.jit_misses == 0, rec.to_dict()
    assert rec.jit_hits > 0
    assert rec.stages_s.get("compile", 0.0) == 0.0
    assert rec.stages_s.get("execute", 0.0) > 0.0


def test_prewarm_zero_compiles_on_lane_n_gt_0(monkeypatch):
    """ISSUE 12 satellite acceptance on a real multi-device host (the CI
    mesh-smoke job forces 8 host devices; self-skips on 1): after a
    per-device prewarm, lane N>0's FIRST dispatch books jit HITs only —
    zero ``compile`` spans, mirroring the existing lane-0 pin."""
    import jax

    from cpzk_tpu.ops import backend as backend_mod
    from cpzk_tpu.ops.backend import TpuBackend, prewarm_executables

    devices = jax.local_devices()
    if len(devices) < 2:
        pytest.skip("needs >1 local device (XLA_FLAGS host device count)")
    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    monkeypatch.setattr(backend_mod, "_AOT_CACHE", {})
    prewarm_executables([6], devices=devices[:2])

    async def main():
        # pinned to lane 1's device — the lane that used to eat the
        # first-dispatch compile while the recorder booked a phantom HIT
        # (pippenger_min pinned for the same reason as the test above)
        batcher = DynamicBatcher(
            TpuBackend(device=devices[1], pippenger_min=1 << 62),
            max_batch=16, window_ms=1.0,
        )
        batcher.start()
        results = await batcher.submit_many(make_entries(6))
        await batcher.stop()
        return results

    assert run(main()) == [None] * 6
    rec = get_flight_recorder().snapshot()[-1]
    assert rec.jit_misses == 0, rec.to_dict()
    assert rec.jit_hits > 0
    assert rec.stages_s.get("compile", 0.0) == 0.0
    assert rec.stages_s.get("execute", 0.0) > 0.0


def test_device_scope_suffixes_jit_keys(monkeypatch):
    """``_jit_first_sight`` keys are per-device facts under
    ``device_scope``: the same shape on another 'device' is a fresh
    first sight (compile attribution per lane), while the unpinned path
    keeps its historical unsuffixed keys."""
    import jax

    from cpzk_tpu.ops import backend as backend_mod

    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    dev = jax.local_devices()[0]
    assert backend_mod._jit_first_sight("combined", 64) is True
    assert backend_mod._jit_first_sight("combined", 64) is False
    with backend_mod.device_scope(dev):
        # same shape, pinned device: a separate first sight
        assert backend_mod._jit_first_sight("combined", 64) is True
        assert backend_mod._jit_first_sight("combined", 64) is False
    assert backend_mod._jit_first_sight("combined", 64) is False
    assert ("combined", 64) in backend_mod._JIT_SEEN
    assert ("combined", 64, f"dev{dev.id}") in backend_mod._JIT_SEEN


def test_tpu_backend_rejects_device_plus_mesh():
    from cpzk_tpu.ops.backend import TpuBackend

    with pytest.raises(ValueError, match="mesh"):
        TpuBackend(mesh_devices=0, device=object())


# --- mesh validation fix -----------------------------------------------------


def test_resolve_mesh_devices_rejects_overcommit():
    """The satellite fix: asking for more devices than exist raises a
    ValueError naming both numbers instead of clamping silently."""
    import jax

    from cpzk_tpu.parallel import resolve_lane_devices, resolve_mesh_devices

    n = jax.device_count()
    with pytest.raises(ValueError, match=rf"mesh_devices={n + 7}.*{n} visible"):
        resolve_mesh_devices(n + 7)
    with pytest.raises(ValueError, match=rf"lanes={n + 7}"):
        resolve_lane_devices(n + 7)
    # unchanged semantics inside bounds
    assert resolve_mesh_devices(None) is None
    assert resolve_mesh_devices(1) is None
    if n == 1:
        assert resolve_mesh_devices(0) is None
        assert resolve_lane_devices(-1) is None
    assert resolve_lane_devices(1) is None


# --- statusz rows ------------------------------------------------------------


def test_statusz_carries_per_lane_rows():
    from cpzk_tpu.observability.opsplane import OpsSources

    router = LaneRouter([CpuBackend(), CpuBackend()])

    async def main():
        batcher = DynamicBatcher(
            CpuBackend(), max_batch=4, window_ms=1.0, router=router,
        )
        batcher.start()
        await batcher.submit_many(make_entries(3))
        doc = OpsSources(batcher=batcher).statusz()
        await batcher.stop()
        return doc

    doc = run(main())
    rows = doc["lanes"]["lanes"]
    assert len(rows) == 2
    assert {row["lane"] for row in rows} == {"0", "1"}
    assert all(row["breaker"] == "closed" for row in rows)
    assert sum(row["dispatches"] for row in rows) == 1
    # single-lane batcher: the block is null, not an empty list
    async def single():
        batcher = DynamicBatcher(CpuBackend(), max_batch=4, window_ms=1.0)
        doc = OpsSources(batcher=batcher).statusz()
        return doc

    assert run(single())["lanes"] is None


# --- config knobs ------------------------------------------------------------


def test_lanes_config_env_layering_and_validation(monkeypatch):
    from cpzk_tpu.server.config import ServerConfig

    monkeypatch.setenv("SERVER_TPU_LANES", "-1")
    monkeypatch.setenv("SERVER_TPU_MESH_THRESHOLD", "32768")
    cfg = ServerConfig()
    cfg._merge_env()
    assert cfg.tpu.lanes == -1
    assert cfg.tpu.mesh_threshold == 32768
    cfg.validate()

    cfg = ServerConfig()
    cfg.tpu.lanes = 0
    with pytest.raises(ValueError, match="lanes"):
        cfg.validate()
    cfg = ServerConfig()
    cfg.tpu.lanes = -2
    with pytest.raises(ValueError, match="lanes"):
        cfg.validate()
    cfg = ServerConfig()
    cfg.tpu.mesh_threshold = -1
    with pytest.raises(ValueError, match="mesh_threshold"):
        cfg.validate()
    # a mesh crossover without multi-lane serving is a misconfiguration
    cfg = ServerConfig()
    cfg.tpu.mesh_threshold = 1000
    cfg.tpu.lanes = 1
    with pytest.raises(ValueError, match="mesh_threshold"):
        cfg.validate()
    cfg.tpu.lanes = -1
    cfg.validate()


def test_lanes_config_keys_documented():
    """CI drift guard (pattern from test_audit.py): the multi-chip
    serving knobs ship in the TOML example, the .env example, and the
    operations-doc knob inventory."""
    toml_text = (ROOT / "config" / "server.toml.example").read_text()
    m = re.search(r"^\[tpu\]$", toml_text, re.M)
    assert m, "[tpu] section missing from config/server.toml.example"
    section = toml_text[m.end():].split("\n[", 1)[0]
    env_text = (ROOT / ".env.example").read_text()
    docs = (ROOT / "docs" / "operations.md").read_text()
    for key in ("lanes", "mesh_threshold"):
        assert re.search(rf"^{key}\s*=", section, re.M), (
            f"[tpu] key {key!r} missing from config/server.toml.example"
        )
        assert f"SERVER_TPU_{key.upper()}" in env_text, (
            f"SERVER_TPU_{key.upper()} missing from .env.example"
        )
        assert f"`tpu.{key}`" in docs, (
            f"`tpu.{key}` missing from the docs/operations.md knob "
            "inventory"
        )


def test_perf_entry_lanes_is_a_config_key(tmp_path):
    """The perf gate treats the lane count as a config-key component:
    same name/n at a different lane count never gates against the
    1-lane baseline (added configs seed their own trajectory), and old
    baselines load as lanes=1."""
    from cpzk_tpu.observability.perf import (
        PerfEntry,
        compare_entries,
        load_snapshot,
        write_snapshot,
    )

    old = [PerfEntry("e2e_curve.grpc", "cpu", 256, 1000.0, "proofs/s")]
    new = [
        PerfEntry("e2e_curve.grpc", "cpu", 256, 990.0, "proofs/s"),
        PerfEntry("e2e_curve.grpc", "cpu", 256, 10.0, "proofs/s", lanes=8),
    ]
    report = compare_entries(old, new, threshold=0.35)
    assert report["passed"], report  # the 8-lane entry is only_new
    assert report["only_new"] == [
        # the key carries every config component: lanes (this test's
        # subject) and the transport wire mode (defaults to "python" —
        # exactly what pre-wire baselines measured)
        ("e2e_curve.grpc", "cpu", 256, "proofs/s", 8, "python")
    ]
    # round-trips: lanes serialized only when != 1, parsed back into key
    path = str(tmp_path / "snap.json")
    write_snapshot(path, new)
    loaded = load_snapshot(path)
    assert sorted(e.key() for e in loaded) == sorted(e.key() for e in new)
    raw = json.loads(pathlib.Path(path).read_text())
    lanes_fields = [e.get("lanes") for e in raw["entries"]]
    assert sorted(lanes_fields, key=str) == [8, None]
