"""Field-layer unit tests: GF(2^255-19), sqrt_ratio_m1, scalar ring."""

import random

from cpzk_tpu.core import field, scalars


def test_constants_consistency():
    # d = -121665/121666
    assert field.fmul(field.D, 121666) == field.fneg(121665)
    # sqrt(-1)^2 == -1
    assert field.fmul(field.SQRT_M1, field.SQRT_M1) == field.P - 1
    # derived ristretto constants
    assert field.ONE_MINUS_D_SQ == (1 - field.D * field.D) % field.P
    assert field.fmul(field.SQRT_AD_MINUS_ONE, field.SQRT_AD_MINUS_ONE) == (-(field.D + 1)) % field.P
    inv2 = field.fmul(field.INVSQRT_A_MINUS_D, field.INVSQRT_A_MINUS_D)
    assert field.fmul(inv2, (-1 - field.D) % field.P) == 1


def test_sqrt_ratio_m1_cases():
    # (0, 0) -> (True, 0)
    assert field.sqrt_ratio_m1(0, 0) == (True, 0)
    # (u, 0) with u != 0 -> (False, 0)
    assert field.sqrt_ratio_m1(3, 0) == (False, 0)
    rng = random.Random(1234)
    squares = 0
    for _ in range(50):
        u = rng.randrange(1, field.P)
        v = rng.randrange(1, field.P)
        ok, r = field.sqrt_ratio_m1(u, v)
        if ok:
            # r^2 * v == u
            assert field.fmul(field.fmul(r, r), v) == u
            squares += 1
        else:
            # r^2 * v == SQRT_M1 * u
            assert field.fmul(field.fmul(r, r), v) == field.fmul(field.SQRT_M1, u)
        assert not field.is_negative(r)
    assert 0 < squares < 50  # both branches exercised


def test_field_inverse_and_abs():
    rng = random.Random(99)
    for _ in range(20):
        a = rng.randrange(1, field.P)
        assert field.fmul(a, field.finv(a)) == 1
        assert field.fabs(a) % 2 == 0
        assert field.fabs(a) in (a, field.P - a)


def test_scalar_ring():
    rng = random.Random(7)
    for _ in range(20):
        a = rng.randrange(scalars.L)
        b = rng.randrange(scalars.L)
        assert scalars.sc_sub(scalars.sc_add(a, b), b) == a
        assert scalars.sc_mul(a, b) == scalars.sc_mul(b, a)
        if a:
            assert scalars.sc_mul(a, scalars.sc_invert(a)) == 1


def test_scalar_canonical_bytes():
    assert scalars.sc_from_bytes_canonical(scalars.sc_to_bytes(5)) == 5
    # ℓ itself is non-canonical
    assert scalars.sc_from_bytes_canonical(scalars.L.to_bytes(32, "little")) is None
    assert scalars.sc_from_bytes_canonical((scalars.L - 1).to_bytes(32, "little")) == scalars.L - 1
    # wide reduction
    wide = (scalars.L + 17).to_bytes(64, "little")
    assert scalars.sc_from_bytes_mod_order_wide(wide) == 17
