"""TPU serving-plane tests: dynamic batching queue, TPU→CPU failover, and
the gRPC integration of both (VERDICT r1 item 3; reference
``src/verifier/service.rs:407-617`` + BASELINE config 5).
"""

import asyncio

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.protocol.batch import (
    BatchRow,
    BatchVerifier,
    CpuBackend,
    FailoverBackend,
    VerifierBackend,
)
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server.batching import DynamicBatcher
from cpzk_tpu.server.service import serve


def run(coro):
    return asyncio.run(coro)


def make_proofs(n, params=None, rng=None):
    rng = rng or SecureRng()
    params = params or Parameters.new()
    out = []
    for _ in range(n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proof = prover.prove_with_transcript(rng, Transcript())
        out.append((prover.statement, proof))
    return params, out


class RecordingBatcher(DynamicBatcher):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.dispatched_sizes = []

    async def _dispatch(self, take):
        self.dispatched_sizes.append(len(take))
        await super()._dispatch(take)


class BrokenBackend(VerifierBackend):
    """Fault injection: always blows up (simulated device loss)."""

    prefers_combined = True

    def __init__(self):
        self.calls = 0

    def verify_combined(self, rows, beta):
        self.calls += 1
        raise RuntimeError("injected TPU failure")

    def verify_each(self, rows):
        self.calls += 1
        raise RuntimeError("injected TPU failure")


# --- DynamicBatcher unit behavior ------------------------------------------


def test_batcher_coalesces_concurrent_submissions():
    params, proofs = make_proofs(6)

    async def main():
        batcher = RecordingBatcher(CpuBackend(), max_batch=64, window_ms=20.0)
        batcher.start()
        results = await asyncio.gather(
            *[batcher.submit(params, st, pr, None) for st, pr in proofs]
        )
        await batcher.stop()
        return batcher, results

    batcher, results = run(main())
    assert results == [None] * 6
    # all six landed in one device batch (20ms window >> submission skew)
    assert batcher.dispatched_sizes == [6]


def test_batcher_flags_invalid_entry_per_index():
    params, proofs = make_proofs(3)

    async def main():
        batcher = DynamicBatcher(CpuBackend(), max_batch=64, window_ms=5.0)
        batcher.start()
        coros = [batcher.submit(params, st, pr, None) for st, pr in proofs]
        # statement/proof mismatch -> must fail at its index only
        coros.append(batcher.submit(params, proofs[0][0], proofs[1][1], None))
        results = await asyncio.gather(*coros)
        await batcher.stop()
        return results

    results = run(main())
    assert [r is None for r in results] == [True, True, True, False]


def test_batcher_respects_max_batch():
    params, proofs = make_proofs(5)

    async def main():
        batcher = RecordingBatcher(CpuBackend(), max_batch=2, window_ms=5.0)
        batcher.start()
        results = await asyncio.gather(
            *[batcher.submit(params, st, pr, None) for st, pr in proofs]
        )
        await batcher.stop()
        return batcher, results

    batcher, results = run(main())
    assert results == [None] * 5
    assert all(s <= 2 for s in batcher.dispatched_sizes)
    assert sum(batcher.dispatched_sizes) == 5


def test_batcher_sheds_load_at_max_queue():
    """Backpressure (ADVICE r2): submissions beyond max_queue raise
    QueueFull instead of growing the queue without bound."""
    from cpzk_tpu.server.batching import QueueFull

    params, proofs = make_proofs(1)
    st, pr = proofs[0]

    async def main():
        # never started -> no dispatcher; but a started batcher with a slow
        # window shows the same behavior, so start it with a long window to
        # keep entries queued while we overfill
        batcher = DynamicBatcher(
            CpuBackend(), max_batch=64, window_ms=5_000.0, max_queue=3
        )
        batcher.start()
        pending = [
            asyncio.ensure_future(batcher.submit(params, st, pr, None))
            for _ in range(3)
        ]
        await asyncio.sleep(0.05)  # let the 3 land in the queue
        with pytest.raises(QueueFull):
            await batcher.submit(params, st, pr, None)
        await batcher.stop()  # drains the 3 queued entries
        return await asyncio.gather(*pending)

    assert run(main()) == [None] * 3


def test_batcher_drains_on_stop():
    params, proofs = make_proofs(2)

    async def main():
        batcher = DynamicBatcher(CpuBackend(), max_batch=64, window_ms=5000.0)
        batcher.start()
        coros = [
            asyncio.ensure_future(batcher.submit(params, st, pr, None))
            for st, pr in proofs
        ]
        await asyncio.sleep(0)  # let submissions enqueue
        await batcher.stop()  # must not wait the 5s window
        return await asyncio.gather(*coros)

    assert run(main()) == [None, None]


# --- failover ---------------------------------------------------------------


def test_failover_backend_degrades_to_cpu():
    params, proofs = make_proofs(4)
    broken = BrokenBackend()
    backend = FailoverBackend(broken, CpuBackend())
    rng = SecureRng()

    bv = BatchVerifier(backend=backend)
    for st, pr in proofs:
        bv.add(params, st, pr)
    assert bv.verify(rng) == [None] * 4
    assert backend.degraded
    assert broken.calls == 1  # first failure degrades permanently

    # subsequent batches never touch the broken primary again
    bv2 = BatchVerifier(backend=backend)
    for st, pr in proofs:
        bv2.add(params, st, pr)
    assert bv2.verify(rng) == [None] * 4
    assert broken.calls == 1

    backend.reset()
    assert not backend.degraded


def test_failover_mid_each_path():
    """Primary dies in verify_each (combined already skipped): fallback
    still returns per-proof ground truth."""

    class EachOnlyBroken(BrokenBackend):
        prefers_combined = False

    params, proofs = make_proofs(2)
    backend = FailoverBackend(EachOnlyBroken(), CpuBackend())
    bv = BatchVerifier(backend=backend)
    bv.add(params, proofs[0][0], proofs[0][1])
    bv.add(params, proofs[0][0], proofs[1][1])  # mismatched -> invalid
    res = bv.verify(SecureRng())
    assert res[0] is None and res[1] is not None
    assert backend.degraded


def test_failover_through_batcher():
    params, proofs = make_proofs(3)
    backend = FailoverBackend(BrokenBackend(), CpuBackend())

    async def main():
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=5.0)
        batcher.start()
        results = await asyncio.gather(
            *[batcher.submit(params, st, pr, None) for st, pr in proofs]
        )
        await batcher.stop()
        return results

    assert run(main()) == [None] * 3
    assert backend.degraded


# --- gRPC integration -------------------------------------------------------


async def _register_and_prove(client, user, rng, params):
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    st = prover.statement
    resp = await client.register(
        user,
        Ristretto255.element_to_bytes(st.y1),
        Ristretto255.element_to_bytes(st.y2),
    )
    assert resp.success
    ch = await client.create_challenge(user)
    t = Transcript()
    t.append_context(bytes(ch.challenge_id))
    proof = prover.prove_with_transcript(rng, t)
    return bytes(ch.challenge_id), proof.to_bytes()


def test_grpc_serving_through_batcher():
    """Concurrent VerifyProof RPCs coalesce into device batches and still
    issue sessions; VerifyProofBatch routes through the same queue."""

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        batcher = RecordingBatcher(CpuBackend(), max_batch=64, window_ms=25.0)
        server, port = await serve(
            state, RateLimiter(10_000, 10_000),
            host="127.0.0.1", port=0, batcher=batcher,
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = [f"user{i}" for i in range(5)]
                pairs = [
                    await _register_and_prove(client, u, rng, params) for u in users
                ]
                # concurrent singles -> coalesced
                resps = await asyncio.gather(
                    *[
                        client.verify_proof(u, cid, pf)
                        for u, (cid, pf) in zip(users, pairs)
                    ]
                )
                assert all(r.success and r.session_token for r in resps)
                assert any(s > 1 for s in batcher.dispatched_sizes), (
                    batcher.dispatched_sizes
                )

                # batch RPC through the same queue (fresh users)
                busers = [f"buser{i}" for i in range(5)]
                pairs2 = [
                    await _register_and_prove(client, u, rng, params) for u in busers
                ]
                br = await client.verify_proof_batch(
                    busers,
                    [cid for cid, _ in pairs2],
                    [pf for _, pf in pairs2],
                )
                assert all(r.success for r in br.results)
        finally:
            await batcher.stop()
            await server.stop(None)

    run(main())


def test_grpc_tpu_backend_end_to_end():
    """A real TpuBackend (JAX CPU device here) behind the batcher serves
    VerifyProof traffic through gRPC — the wiring VERDICT r1 flagged as
    absent."""
    from cpzk_tpu.ops.backend import TpuBackend

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        backend = FailoverBackend(TpuBackend(), CpuBackend())
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=25.0)
        server, port = await serve(
            state, RateLimiter(10_000, 10_000),
            host="127.0.0.1", port=0, backend=backend, batcher=batcher,
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = [f"tpuuser{i}" for i in range(3)]
                pairs = [
                    await _register_and_prove(client, u, rng, params) for u in users
                ]
                resps = await asyncio.gather(
                    *[
                        client.verify_proof(u, cid, pf)
                        for u, (cid, pf) in zip(users, pairs)
                    ]
                )
                assert all(r.success and r.session_token for r in resps)
                assert not backend.degraded  # the JAX path actually served
        finally:
            await batcher.stop()
            await server.stop(None)

    run(main())


def test_multihost_single_process_noop():
    """multihost.initialize is a no-op for single-process jobs, and the
    global mesh covers all (virtual) devices."""
    from cpzk_tpu.parallel import multihost

    multihost.initialize()
    idx, count = multihost.process_info()
    assert (idx, count) == (0, 1)
    mesh = multihost.global_batch_mesh()
    import jax

    assert mesh.devices.size == len(jax.devices())


def test_batcher_overlaps_host_prep_with_device_compute():
    """Double-buffering through the dispatch lane (PP analog, SURVEY
    §2.3): while batch 1 is blocked inside the backend on the device
    thread, batch 2's host prep completes on the prep thread and the
    prepared batch waits in the lane's staging slot — host work of batch
    N+1 overlaps device work of batch N instead of queueing behind it."""
    import threading

    release = threading.Event()
    entered = threading.Event()

    class SlowBackend(VerifierBackend):
        prefers_combined = False

        def verify_combined(self, rows, beta):  # pragma: no cover
            raise AssertionError("unused")

        def verify_each(self, rows):
            entered.set()
            release.wait(5.0)
            return [True] * len(rows)

    params, proofs = make_proofs(4)

    async def main():
        batcher = DynamicBatcher(
            SlowBackend(), max_batch=2, window_ms=1.0, pipeline_depth=2
        )
        batcher.start()
        coros = [batcher.submit(params, st, pr, None) for st, pr in proofs]
        fut = asyncio.gather(*coros)
        # The assertion happens BEFORE release.set(): under a serial
        # (non-overlapping) lane, batch 2 would never be prepared while
        # batch 1 blocks in the backend, so the poll loop exhausts.
        staged = False
        for _ in range(200):
            if entered.is_set() and batcher._lane.depths()[1] >= 1:
                staged = True
                break
            await asyncio.sleep(0.02)
        release.set()
        results = await fut
        await batcher.stop()
        return results, staged

    results, staged = run(main())
    assert results == [None] * 4
    assert staged, (
        "batch 2 was never host-prepared while batch 1 held the device thread"
    )
