"""Fast fuzz smoke: bounded runs of the fuzz/ targets inside the default
suite (resilience subsystem satellite — the adversarial parser surfaces
get exercised on every CI run, not only when someone remembers to run the
standalone fuzzers).

Uses the harness's built-in seeded mutation engine via
``common.run_bounded`` (deterministic; Atheris, when installed, is
deliberately bypassed because it ignores bounds).  Budget: well under
30 s for both targets together on one core.
"""

import importlib.util
import os
import sys

import pytest

FUZZ_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "fuzz"
)


def _load(name: str):
    if FUZZ_DIR not in sys.path:
        sys.path.insert(0, FUZZ_DIR)  # targets do `from common import ...`
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(FUZZ_DIR, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize(
    "target,runs",
    [
        ("fuzz_proof_deserialization", 120),
        ("fuzz_statement_validation", 400),
        ("fuzz_wal_replay", 300),
        ("fuzz_admission", 400),
        ("fuzz_lint", 150),
        ("fuzz_audit_log", 400),
        ("fuzz_partition_map", 400),
        ("fuzz_wire_parse", 400),
    ],
)
def test_fuzz_target_smoke(target, runs):
    common = _load("common")
    mod = _load(target)
    done = common.run_bounded(mod.one_input, mod._seeds(), runs=runs, seed=1234)
    assert done == runs  # raises on the first invariant violation
