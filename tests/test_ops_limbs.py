"""Differential tests: JAX limb field arithmetic vs the integer-exact host
field (:mod:`cpzk_tpu.core.field`). Runs on the JAX CPU backend (conftest
forces ``JAX_PLATFORMS=cpu`` with a virtual 8-device topology)."""

import secrets

import numpy as np
import pytest

from cpzk_tpu.core import field as hf
from cpzk_tpu.ops import limbs

N = 64  # batch size for randomized differential checks


def rand_fes(n: int) -> list[int]:
    vals = [secrets.randbelow(hf.P) for _ in range(n - 4)]
    # adversarial corners
    vals += [0, 1, hf.P - 1, hf.P - 19]
    return vals


@pytest.fixture(scope="module")
def ab():
    a = rand_fes(N)
    b = rand_fes(N)
    return a, b, limbs.ints_to_limbs(a), limbs.ints_to_limbs(b)


def check(expected: list[int], got) -> None:
    got_ints = limbs.limbs_to_ints(np.asarray(got))
    assert [v % hf.P for v in got_ints] == [v % hf.P for v in expected]


def test_roundtrip_conversions(ab):
    a, _, la, _ = ab
    assert limbs.limbs_to_ints(la) == a
    # single-int path
    assert limbs.limbs_to_int(limbs.int_to_limbs(12345)) == 12345


def test_add_sub_neg(ab):
    a, b, la, lb = ab
    check([hf.fadd(x, y) for x, y in zip(a, b)], limbs.add(la, lb))
    check([hf.fsub(x, y) for x, y in zip(a, b)], limbs.sub(la, lb))
    check([hf.fneg(x) for x in a], limbs.neg(la))


def test_mul_square(ab):
    a, b, la, lb = ab
    check([hf.fmul(x, y) for x, y in zip(a, b)], limbs.mul(la, lb))
    check([hf.fmul(x, x) for x in a], limbs.square(la))


def test_mul_small(ab):
    a, _, la, _ = ab
    check([x * 121 % hf.P for x in a], limbs.mul_small(la, 121))
    check([(-x * 2) % hf.P for x in a], limbs.mul_small(la, -2))


def test_canonical_idempotent_on_large_values():
    vals = [hf.P, hf.P + 1, 2 * hf.P + 5, (1 << 260) - 1, hf.P - 1]
    la = np.stack([limbs.int_to_limbs(v) for v in vals], axis=-1)
    check([v % hf.P for v in vals], limbs.canonical(la))


def test_inv(ab):
    a, _, la, _ = ab
    nz = [x if x != 0 else 1 for x in a]
    lnz = limbs.ints_to_limbs(nz)
    check([hf.finv(x) for x in nz], limbs.inv(lnz))


def test_is_negative_fabs_eq(ab):
    a, b, la, lb = ab
    assert list(np.asarray(limbs.is_negative(la))) == [hf.is_negative(x) for x in a]
    check([hf.fabs(x) for x in a], limbs.fabs(la))
    assert list(np.asarray(limbs.eq(la, la))) == [True] * N
    eq_ab = list(np.asarray(limbs.eq(la, lb)))
    assert eq_ab == [x == y for x, y in zip(a, b)]


def test_sqrt_ratio_m1(ab):
    a, b, la, lb = ab
    ok_host, r_host = zip(*[hf.sqrt_ratio_m1(x, y) for x, y in zip(a, b)])
    ok_dev, r_dev = limbs.sqrt_ratio_m1(la, lb)
    assert list(np.asarray(ok_dev)) == list(ok_host)
    check(list(r_host), r_dev)


def test_sqrt_ratio_corner_cases():
    # (0,0) -> (True, 0); (u!=0, v=0) -> (False, 0)
    u = limbs.ints_to_limbs([0, 5])
    v = limbs.ints_to_limbs([0, 0])
    ok, r = limbs.sqrt_ratio_m1(u, v)
    assert list(np.asarray(ok)) == [True, False]
    check([0, 0], r)


def test_loose_limb_bounds_adversarial():
    """Overflow-safety check for the loose-carry discipline: feed limb
    vectors at the +/-BOUND extremes (valid redundant representations that
    never arise from canonical inputs) through add/sub/mul and compare with
    exact big-int arithmetic."""
    BOUND = 9500
    patterns = [
        np.full(limbs.NLIMBS, BOUND, dtype=np.int32),
        np.full(limbs.NLIMBS, -BOUND, dtype=np.int32),
        np.asarray([BOUND if i % 2 else -BOUND for i in range(limbs.NLIMBS)], dtype=np.int32),
        np.asarray([-BOUND] + [BOUND] * (limbs.NLIMBS - 1), dtype=np.int32),
    ]
    la = np.stack(patterns, axis=-1)  # [20, 4] limb-major
    vals = [limbs.limbs_to_int(p) for p in patterns]
    for out, expect in (
        (limbs.mul(la, la), [v * v for v in vals]),
        (limbs.mul(la, la[:, ::-1].copy()), [v * w for v, w in zip(vals, vals[::-1])]),
        (limbs.add(la, la), [2 * v for v in vals]),
        (limbs.sub(la, la[:, ::-1].copy()), [v - w for v, w in zip(vals, vals[::-1])]),
        (limbs.square(limbs.add(la, la[:, ::-1].copy())), [(v + w) ** 2 for v, w in zip(vals, vals[::-1])]),
    ):
        check([e % hf.P for e in expect], out)

    # loose outputs stay mul-safe: |limb| <= BOUND after every op
    for op_out in (limbs.mul(la, la), limbs.add(la, la), limbs.sub(la, la[:, ::-1].copy())):
        assert int(np.abs(np.asarray(op_out)).max()) <= BOUND


def test_bytes_roundtrip(ab):
    a, _, la, _ = ab
    enc = np.asarray(limbs.to_bytes_le(la))  # [32, n]
    expected = [hf.fe_to_bytes(x) for x in a]
    assert [bytes(enc[:, j].astype(np.uint8).tobytes()) for j in range(N)] == expected
    back = limbs.from_bytes_le(enc)
    check(a, back)


def test_bytes_to_limbs_vectorized(ab):
    a, _, _, _ = ab
    rows = np.stack([np.frombuffer(hf.fe_to_bytes(x), dtype=np.uint8) for x in a])
    check(a, limbs.bytes_to_limbs(rows))


def test_mul_variants_bit_exact():
    """The matmulfold mul variant agrees with the schoolbook path and the
    host oracle (CPZK_MUL A/B safety — VERDICT r2 item 2), including on
    mixed-sign-half loose carried-form inputs (the shape that overflowed
    the removed Karatsuba variant)."""
    import secrets

    import jax

    from cpzk_tpu.ops import limbs as m

    xs = [secrets.randbelow(m.P) for _ in range(32)] + [m.P - 1, 0, 1]
    ys = [secrets.randbelow(m.P) for _ in range(32)] + [m.P - 1, m.P - 1, 2]
    a, b = m.ints_to_limbs(xs), m.ints_to_limbs(ys)
    exp = [x * y % m.P for x, y in zip(xs, ys)]

    def run(variant):
        old = m.MUL_VARIANT
        m.MUL_VARIANT = variant
        try:
            # jit cache keys on the traced graph, not the module global:
            # trace fresh each time
            return m.limbs_to_ints(m.canonical(m.mul(a, b)))
        finally:
            m.MUL_VARIANT = old

    for variant in ("schoolbook", "matmulfold"):
        assert run(variant) == exp, variant

    # adversarial max-limb carried-form inputs with MIXED-SIGN halves —
    # the shape that overflowed the removed Karatsuba variant's middle
    # product (review r3): low half +bound, high half -bound
    import numpy as np

    am = np.concatenate([np.full((10, 3), 9500), np.full((10, 3), -9500)]).astype(np.int32)
    bm = np.concatenate([np.full((10, 3), -9500), np.full((10, 3), 9500)]).astype(np.int32)
    ia, ib = m.limbs_to_int(am[:, 0]), m.limbs_to_int(bm[:, 0])
    for variant in ("schoolbook", "matmulfold"):
        old = m.MUL_VARIANT
        m.MUL_VARIANT = variant
        try:
            out = m.limbs_to_ints(m.canonical(m.mul(am, bm)))
        finally:
            m.MUL_VARIANT = old
        assert all(v == ia * ib % m.P for v in out), variant
