"""Test harness config.

JAX-based tests run on the CPU backend with a virtual 8-device topology so
multi-chip sharding logic is exercised without TPU hardware (SURVEY.md §4
multi-node story).

The axon TPU plugin's sitecustomize imports jax at interpreter startup with
``JAX_PLATFORMS=axon`` already in the environment, so mutating ``os.environ``
here is too late for jax's config cache — ``jax.config.update`` is the only
reliable override.  ``XLA_FLAGS`` is still read at CPU-client creation time,
which happens after this module runs, so the env var works for the device
count.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compilation cache: the crypto kernels are compile-heavy and
# shape-stable, so warm runs of the device test tier drop from minutes to
# seconds.  Safe to share across processes; keyed by HLO + compile options.
_cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
