"""Test harness config.

JAX-based tests run on a virtual 8-device CPU mesh so multi-chip sharding
logic is exercised without TPU hardware (SURVEY.md §4 multi-node story).
Env vars must be set before the first ``import jax`` anywhere in the test
process.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the axon TPU plugin ignores JAX_PLATFORMS; JAX_PLATFORM_NAME still wins
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
