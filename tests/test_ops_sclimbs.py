"""Device scalar-field (mod l) arithmetic vs the host oracle: Barrett
reduction, products, sums, wide challenge reduction — bit-exact."""

import secrets

import numpy as np

import jax

from cpzk_tpu.core.scalars import L
from cpzk_tpu.ops import sclimbs as m


def rand_scalars(n):
    return [secrets.randbelow(L) for _ in range(n)]


def test_mul_matches_host():
    n = 33
    xs, ys = rand_scalars(n), rand_scalars(n)
    # adversarial edges
    xs[:4] = [0, 1, L - 1, L - 1]
    ys[:4] = [L - 1, L - 1, L - 1, 1]
    out = m.limbs_to_ints(jax.jit(m.mul)(m.ints_to_limbs(xs), m.ints_to_limbs(ys)))
    assert out == [x * y % L for x, y in zip(xs, ys)]


def test_add_matches_host():
    n = 17
    xs, ys = rand_scalars(n), rand_scalars(n)
    xs[0], ys[0] = L - 1, L - 1
    out = m.limbs_to_ints(jax.jit(m.add)(m.ints_to_limbs(xs), m.ints_to_limbs(ys)))
    assert out == [(x + y) % L for x, y in zip(xs, ys)]


def test_wide_reduction_matches_host():
    n = 9
    blobs = [secrets.token_bytes(64) for _ in range(n)]
    blobs[0] = b"\xff" * 64   # max 512-bit value
    blobs[1] = bytes(64)      # zero
    cols = np.frombuffer(b"".join(blobs), dtype=np.uint8).reshape(n, 64)
    out = m.limbs_to_ints(jax.jit(m.reduce_wide)(m.bytes_wide_to_limbs(cols)))
    assert out == [int.from_bytes(b, "little") % L for b in blobs]


def test_sum_mod_l_matches_host():
    for n in (1, 7, 1024):
        xs = rand_scalars(n)
        got = m.limbs_to_ints(m.sum_mod_l(m.ints_to_limbs(xs)))[0]
        assert got == sum(xs) % L, n


def test_mul_chain_stays_canonical():
    """Outputs feed back as inputs (canonical-in/canonical-out contract)."""
    xs = rand_scalars(5)
    a = m.ints_to_limbs(xs)
    acc = a
    exp = list(xs)
    fn = jax.jit(m.mul)
    for _ in range(4):
        acc = fn(acc, a)
        exp = [e * x % L for e, x in zip(exp, xs)]
    assert m.limbs_to_ints(acc) == exp


def test_to_windows_matches_host():
    from cpzk_tpu.ops.curve import scalars_to_windows

    xs = rand_scalars(21) + [0, 1, L - 1]
    got = np.asarray(jax.jit(m.to_windows)(m.ints_to_limbs(xs)))
    exp = scalars_to_windows(xs)
    assert got.shape == exp.shape and (got == exp).all()


def test_device_rlc_prep_end_to_end(monkeypatch):
    """CPZK_DEVICE_RLC=1 routes the combined check's scalar prep through
    the device plane with identical accept/reject decisions."""
    from cpzk_tpu import BatchVerifier, Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.backend import TpuBackend

    rng, params = SecureRng(), Parameters.new()
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng))) for _ in range(5)
    ]
    proofs = [p.prove_with_transcript(rng, Transcript()) for p in provers]

    monkeypatch.setenv("CPZK_DEVICE_RLC", "1")
    monkeypatch.setenv("CPZK_PIPPENGER_MIN", "9999")  # force the rowcombined path

    # all-valid batch accepts via the device-prep combined fast path
    bv = BatchVerifier(backend=TpuBackend())
    for p, pf in zip(provers, proofs):
        bv.add(params, p.statement, pf)
    assert bv.verify(rng) == [None] * 5

    # one bad row: combined fails, per-proof fallback flags index 5 only
    bv = BatchVerifier(backend=TpuBackend())
    for p, pf in zip(provers, proofs):
        bv.add(params, p.statement, pf)
    bv.add(params, provers[0].statement, proofs[1])
    res = bv.verify(rng)
    assert [r is None for r in res] == [True] * 5 + [False]


def test_device_rlc_windows_match_host():
    """The four device-derived window columns are bit-identical to the
    host big-int products for the same rows and beta."""
    import os

    import numpy as np

    from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.backend import _pad_pow2, _rlc_windows_device, _windows
    from cpzk_tpu.protocol.batch import BatchVerifier

    rng, params = SecureRng(), Parameters.new()
    bv = BatchVerifier()
    for _ in range(3):
        p = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        bv.add(params, p.statement, p.prove_with_transcript(rng, Transcript()))
    rows = bv.prepare_rows(rng)
    beta = Ristretto255.random_scalar(rng)

    n, b = len(rows), beta.value
    pad = _pad_pow2(n + 1)
    a = [r.alpha.value for r in rows]
    c = [r.c.value for r in rows]
    s = [r.s.value for r in rows]
    ac = [x * y % L for x, y in zip(a, c)]
    ba = [b * x % L for x in a]
    bac = [b * x % L for x in ac]
    sum_as = sum(x * y for x, y in zip(a, s)) % L
    host_cols = (
        _windows(a + [(L - sum_as) % L], pad),
        _windows(ac + [(L - b * sum_as % L) % L], pad),
        _windows(ba, pad),
        _windows(bac, pad),
    )
    dev_cols = _rlc_windows_device(rows, beta, pad)
    for hcol, dcol in zip(host_cols, dev_cols):
        assert (np.asarray(hcol) == np.asarray(dcol)).all()


def test_to_signed_digits_matches_host():
    from cpzk_tpu.ops.msm import scalars_to_signed_digits

    for c in (4, 8, 13, 16):
        xs = rand_scalars(9) + [0, 1, L - 1]
        got = np.asarray(m.to_signed_digits(m.ints_to_limbs(xs), c))
        exp = scalars_to_signed_digits(xs, c)
        assert got.shape == exp.shape and (got == exp).all(), c


def test_device_rlc_pippenger_path(monkeypatch):
    """CPZK_DEVICE_RLC=1 with the Pippenger branch engaged (n >= min):
    same accept/reject, digits from the device scalar plane."""
    from cpzk_tpu import BatchVerifier, Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.backend import TpuBackend

    rng, params = SecureRng(), Parameters.new()
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng))) for _ in range(6)
    ]
    proofs = [p.prove_with_transcript(rng, Transcript()) for p in provers]

    monkeypatch.setenv("CPZK_DEVICE_RLC", "1")
    monkeypatch.setenv("CPZK_PIPPENGER_MIN", "2")  # force the MSM path
    import importlib

    import cpzk_tpu.ops.backend as backend_mod

    importlib.reload(backend_mod)  # PIPPENGER_MIN_ROWS is read at import

    bv = BatchVerifier(backend=backend_mod.TpuBackend())
    for p, pf in zip(provers, proofs):
        bv.add(params, p.statement, pf)
    assert bv.verify(rng) == [None] * 6

    bv = BatchVerifier(backend=backend_mod.TpuBackend())
    for p, pf in zip(provers, proofs):
        bv.add(params, p.statement, pf)
    bv.add(params, provers[0].statement, proofs[1])
    res = bv.verify(rng)
    assert [r is None for r in res] == [True] * 6 + [False]

    importlib.reload(backend_mod)  # restore default PIPPENGER_MIN_ROWS


def test_device_rlc_composes_with_sharded_msm(monkeypatch):
    """CPZK_DEVICE_RLC digits feed the mesh-sharded Pippenger check
    unchanged (8 virtual devices via conftest's XLA_FLAGS)."""
    import importlib

    import jax

    from cpzk_tpu import BatchVerifier, Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255

    if jax.device_count() < 2:
        import pytest

        pytest.skip("needs the virtual multi-device CPU mesh")

    monkeypatch.setenv("CPZK_DEVICE_RLC", "1")
    monkeypatch.setenv("CPZK_PIPPENGER_MIN", "2")
    import cpzk_tpu.ops.backend as backend_mod

    importlib.reload(backend_mod)

    rng, params = SecureRng(), Parameters.new()
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng))) for _ in range(5)
    ]
    proofs = [p.prove_with_transcript(rng, Transcript()) for p in provers]
    backend = backend_mod.TpuBackend(mesh_devices=0)
    assert backend._sharded_msm is not None

    bv = BatchVerifier(backend=backend)
    for p, pf in zip(provers, proofs):
        bv.add(params, p.statement, pf)
    bv.add(params, provers[0].statement, proofs[1])
    res = bv.verify(rng)
    assert [r is None for r in res] == [True] * 5 + [False]

    importlib.reload(backend_mod)
