"""N-partition fleet (ROADMAP item 3): versioned partition map,
wrong-partition redirect, live splitting over the WAL replication plane.

Covers the map contract (routing totality, disjoint+exhaustive
validation, versioned split, digest), the server-side ownership
enforcement + redirect trailers (incl. the N=1 fast path the perf gate
leans on), the client-side channel pool / redirect / batch fan-out, the
crash-resumable split flow at every FaultPlan stage, the rotated
proof-log + shipping tail (PR 9), the ``[fleet]`` config surface, and
the 3-partition chaos acceptance: SIGKILL one partition's primary — that
partition auto-promotes while the other two serve uninterrupted, and a
stale-map client converges in one redirect.
"""

import asyncio
import dataclasses
import json
import os
import pathlib
import re

import grpc
import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Witness
from cpzk_tpu.client.rpc import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.durability import DurabilityManager
from cpzk_tpu.fleet import (
    HASH_SPACE,
    FleetRouter,
    PartitionMap,
    run_split,
    user_hash,
)
from cpzk_tpu.fleet.split import SPLIT_CRASH_POINTS, SplitError, manifest_path
from cpzk_tpu.replication import SegmentShipper, StandbyReplica
from cpzk_tpu.resilience.faults import CrashPoint, FaultPlan
from cpzk_tpu.server import metrics
from cpzk_tpu.server.config import (
    DurabilitySettings,
    FleetSettings,
    RateLimiter,
    ReplicationSettings,
    ServerConfig,
)
from cpzk_tpu.server.service import serve
from cpzk_tpu.server.state import ServerState, UserData

ROOT = pathlib.Path(__file__).resolve().parent.parent

rng = SecureRng()
params = Parameters.new()


def run(coro):
    return asyncio.run(coro)


def make_statement():
    return Prover(params, Witness(Ristretto255.random_scalar(rng))).statement


def uid_on_partition(pmap: PartitionMap, index: int, tag: str = "u") -> str:
    """A user id the map routes to partition ``index``."""
    i = 0
    while True:
        uid = f"{tag}{i}"
        if pmap.partition_for(uid).index == index:
            return uid
        i += 1


async def wait_for(predicate, timeout=8.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not reached in time")
        await asyncio.sleep(interval)


# --- the partition map ------------------------------------------------------


class TestPartitionMap:
    def test_uniform_routing_is_total_and_stable(self):
        pmap = PartitionMap.uniform(["a:1", "b:2", "c:3"])
        assert pmap.version == 1
        # totality over arbitrary ids: every id lands on exactly one
        # partition, and that partition's ranges cover its hash
        for uid in ["alice", "", "猫" * 40, "x" * 300, "u-1.2_3", "\x00"]:
            p = pmap.partition_for(uid)
            assert p.covers(user_hash(uid))
            assert sum(
                q.covers(user_hash(uid)) for q in pmap.partitions
            ) == 1
        # placement is the stable crc32 the state shards use
        assert user_hash("alice") == __import__("zlib").crc32(b"alice")

    def test_ranges_are_disjoint_and_exhaustive(self):
        pmap = PartitionMap.uniform([f"h:{i}" for i in range(7)])
        spans = sorted(
            (lo, hi) for p in pmap.partitions for lo, hi in p.ranges
        )
        cursor = 0
        for lo, hi in spans:
            assert lo == cursor
            cursor = hi
        assert cursor == HASH_SPACE

    @pytest.mark.parametrize("mutate,match", [
        (lambda d: d.update(schema="nope"), "schema"),
        (lambda d: d.update(version=0), "version"),
        (lambda d: d["partitions"][0].update(address=""), "address"),
        (lambda d: d["partitions"][0]["ranges"][0].__setitem__(1, 99),
         "overlap|gap"),
        (lambda d: d["partitions"].pop(), "gap|indexes"),
        (lambda d: d["partitions"][0].update(index=5), "indexes"),
        (lambda d: d.update(digest="0" * 64), "digest"),
        (lambda d: d.update(partitions="zzz"), "list"),
    ])
    def test_from_doc_rejects_malformed(self, mutate, match):
        doc = PartitionMap.uniform(["a:1", "b:2"]).to_doc()
        had_digest = doc.pop("digest")
        mutate(doc)
        if "digest" not in doc:
            doc.pop("digest", None)
        with pytest.raises(ValueError, match=match):
            PartitionMap.from_doc(doc)
        # untouched doc (with its real digest) still parses
        good = PartitionMap.uniform(["a:1", "b:2"]).to_doc()
        assert PartitionMap.from_doc(good).digest == had_digest

    def test_split_bumps_version_moves_upper_half(self):
        pmap = PartitionMap.uniform(["a:1", "b:2", "c:3"])
        new_map, moved = pmap.split(1, "d:4")
        assert new_map.version == pmap.version + 1
        assert len(new_map.partitions) == 4
        assert new_map.partitions[3].address == "d:4"
        assert new_map.partitions[3].ranges == moved
        # non-moved users keep their owner; moved users go 1 -> 3
        for i in range(500):
            uid = f"u{i}"
            before = pmap.partition_for(uid).index
            after = new_map.partition_for(uid).index
            if before != after:
                assert (before, after) == (1, 3)
                assert any(
                    lo <= user_hash(uid) < hi for lo, hi in moved
                )

    def test_store_load_roundtrip_and_digest(self, tmp_path):
        pmap, _ = PartitionMap.uniform(["a:1", "b:2"]).split(0, "c:3")
        path = str(tmp_path / "map.json")
        pmap.store(path)
        loaded = PartitionMap.load(path)
        assert loaded.version == pmap.version == 2
        assert loaded.digest == pmap.digest
        assert loaded.to_json() == pmap.to_json()
        assert loaded.index_of_address("c:3") == 2
        with pytest.raises(ValueError, match="not in the partition map"):
            loaded.index_of_address("nope:9")

    def test_router_n1_fast_path_never_hashes(self, monkeypatch):
        """A single-partition map must short-circuit before any hash —
        the structural guarantee behind the perf-gate acceptance."""
        router = FleetRouter(PartitionMap.uniform(["only:1"]), 0)

        def boom(_uid):  # pragma: no cover - the point is it never runs
            raise AssertionError("N=1 owns() computed a hash")

        monkeypatch.setattr(
            "cpzk_tpu.fleet.partition_map.user_hash", boom
        )
        assert router.owns("anything") is True
        assert router.owns("") is True

    def test_router_reload_adopts_strictly_newer(self, tmp_path):
        path = str(tmp_path / "map.json")
        v1 = PartitionMap.uniform(["a:1", "b:2"])
        v1.store(path)
        router = FleetRouter(v1, 0, map_path=path)
        assert router.reload() is False  # same version: no-op
        v2, _ = v1.split(1, "c:3")
        v2.store(path)
        assert router.reload() is True
        assert router.map.version == 2
        assert router.status()["map_version"] == 2


# --- server-side enforcement over real gRPC ---------------------------------


async def _two_partition_fleet():
    """Two plain servers + a v1 map over their real ports; routers
    installed on both.  Returns (pmap, states, servers, ports)."""
    states = [ServerState(), ServerState()]
    srv0, p0 = await serve(states[0], RateLimiter(10**6, 10**6), port=0)
    srv1, p1 = await serve(states[1], RateLimiter(10**6, 10**6), port=0)
    pmap = PartitionMap.uniform([f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"])
    srv0.auth_service.fleet = FleetRouter(pmap, 0)
    srv1.auth_service.fleet = FleetRouter(pmap, 1)
    return pmap, states, (srv0, srv1), (p0, p1)


class TestEnforcement:
    def test_wrong_partition_redirect_trailers(self):
        from cpzk_tpu.client.__main__ import do_login, do_register

        async def main():
            pmap, states, servers, ports = await _two_partition_fleet()
            u1 = uid_on_partition(pmap, 1)
            before = metrics.read("fleet.redirects")
            try:
                # correct routing serves normally end to end
                c = AuthClient(partition_map=pmap)
                assert "Registered" in await do_register(c, u1, "pw")
                assert "Login OK" in await do_login(c, u1, "pw")
                assert u1 in states[1]._users and u1 not in states[0]._users
                assert c.redirects == 0
                await c.close()

                # a mapless client hitting the wrong box gets the full
                # redirect contract: FAILED_PRECONDITION + both trailers
                c2 = AuthClient(f"127.0.0.1:{ports[0]}")
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await c2.create_challenge(u1)
                assert exc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
                tmd = {
                    k: v for k, v in exc.value.trailing_metadata() or ()
                }
                assert tmd["cpzk-partition-map-version"] == "1"
                assert tmd["cpzk-partition-owner"] == f"127.0.0.1:{ports[1]}"
                assert "partition 1" in exc.value.details()
                await c2.close()
                assert metrics.read("fleet.redirects") >= before + 1
                assert servers[0].auth_service.fleet.redirects >= 1
            finally:
                for s in servers:
                    await s.stop(None)

        run(main())

    def test_write_time_fence_answers_the_redirect_contract(self):
        """A ``WrongPartition`` raised by the state's write-time owner
        fence — the map-flip-lands-mid-handler case the entry check
        cannot see — surfaces over gRPC exactly like the entry check:
        FAILED_PRECONDITION with both routing trailers, and the
        mutation left no trace."""

        async def main():
            pmap, states, servers, ports = await _two_partition_fleet()
            u0 = uid_on_partition(pmap, 0)
            # serve() installs the fence when it gets a fleet at
            # construction; this harness assigns fleet post-hoc, so
            # install a fence that rejects u0 even though the entry
            # check passes — standing in for a flip landing after the
            # entry check but before the mutation
            states[0].attach_owner_fence(
                lambda uid: (
                    f"wrong partition: user '{uid}' moved"
                    if uid == u0 else None
                )
            )
            try:
                stmt = make_statement()
                eb = Ristretto255.element_to_bytes
                c = AuthClient(f"127.0.0.1:{ports[0]}")
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await c.register(u0, eb(stmt.y1), eb(stmt.y2))
                assert exc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
                assert "wrong partition" in exc.value.details()
                tmd = {
                    k: v for k, v in exc.value.trailing_metadata() or ()
                }
                assert tmd["cpzk-partition-map-version"] == "1"
                assert tmd["cpzk-partition-owner"] == f"127.0.0.1:{ports[0]}"
                assert u0 not in states[0]._users
                await c.close()
            finally:
                for s in servers:
                    await s.stop(None)

        run(main())

    def test_verify_proof_redirect_never_consumes_challenge(self):
        """The redirect fires BEFORE consume_challenge: the same proof
        re-sent to the owner must still authenticate."""
        from cpzk_tpu.client.kdf import password_to_scalar
        from cpzk_tpu.core.transcript import Transcript

        async def main():
            pmap, states, servers, ports = await _two_partition_fleet()
            u1 = uid_on_partition(pmap, 1)
            try:
                prover = Prover(params, Witness(password_to_scalar("pw", u1)))
                eb = Ristretto255.element_to_bytes
                owner = AuthClient(f"127.0.0.1:{ports[1]}")
                await owner.register(
                    u1, eb(prover.statement.y1), eb(prover.statement.y2)
                )
                ch = await owner.create_challenge(u1)
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                wire = prover.prove_with_transcript(SecureRng(), t).to_bytes()

                wrong = AuthClient(f"127.0.0.1:{ports[0]}")
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await wrong.verify_proof(u1, cid, wire)
                assert exc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
                await wrong.close()
                # the challenge survived the redirect — the owner accepts
                resp = await owner.verify_proof(u1, cid, wire)
                assert resp.success
                await owner.close()
            finally:
                for s in servers:
                    await s.stop(None)

        run(main())

    def test_batch_and_stream_answer_misrouted_entries_individually(self):
        from cpzk_tpu.client.kdf import password_to_scalar
        from cpzk_tpu.core.transcript import Transcript

        async def main():
            pmap, states, servers, ports = await _two_partition_fleet()
            u0 = uid_on_partition(pmap, 0)
            u1 = uid_on_partition(pmap, 1)
            try:
                eb = Ristretto255.element_to_bytes
                pr0 = Prover(params, Witness(password_to_scalar("p0", u0)))
                pr1 = Prover(params, Witness(password_to_scalar("p1", u1)))
                c0 = AuthClient(f"127.0.0.1:{ports[0]}")
                # mixed batch at partition 0: u0 lands, u1 redirects
                resp = await c0.register_batch(
                    [u0, u1],
                    [eb(pr0.statement.y1), eb(pr1.statement.y1)],
                    [eb(pr0.statement.y2), eb(pr1.statement.y2)],
                )
                assert resp.results[0].success
                assert not resp.results[1].success
                assert "wrong partition" in resp.results[1].message
                assert u1 not in states[0]._users

                # stream: the misrouted entry gets a per-entry failure,
                # the owned entry verifies, the stream survives
                ch = await c0.create_challenge(u0)
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                wire = pr0.prove_with_transcript(SecureRng(), t).to_bytes()
                verdicts = []
                async for v in c0.verify_proof_stream(
                    [(u0, cid, wire), (u1, b"\x01" * 32, wire)]
                ):
                    verdicts.append(v)
                assert len(verdicts) == 2
                assert verdicts[0].ok
                assert not verdicts[1].ok
                assert "wrong partition" in verdicts[1].message
                await c0.close()
            finally:
                for s in servers:
                    await s.stop(None)

        run(main())

    def test_standby_refusal_counts_admission_shed(self, tmp_path):
        """Satellite fix: the standby's UNAVAILABLE abort (and the
        redirect abort) are charged to counters the SLO burn math can
        see, not silently dropped."""

        async def main():
            sstate = ServerState()
            smgr = DurabilityManager(
                sstate, DurabilitySettings(enabled=True),
                str(tmp_path / "s.json"),
            )
            await smgr.recover()
            replica = StandbyReplica(
                sstate, smgr,
                ReplicationSettings(
                    enabled=True, role="standby", lease_ms=5000,
                    renew_interval_ms=100,
                ),
            )
            sserver, sport = await serve(
                sstate, RateLimiter(10**6, 10**6), port=0, replica=replica
            )
            before = metrics.read("admission.shed.standby")
            try:
                async with AuthClient(f"127.0.0.1:{sport}") as c:
                    with pytest.raises(grpc.aio.AioRpcError) as exc:
                        await c.create_challenge("alice")
                    assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
                assert metrics.read("admission.shed.standby") == before + 1
            finally:
                await replica.stop()
                await sserver.stop(None)

        run(main())


# --- client-side routing ----------------------------------------------------


class TestClientRouting:
    def test_batch_fanout_preserves_entry_order(self):
        from cpzk_tpu.client.kdf import password_to_scalar
        from cpzk_tpu.core.transcript import Transcript

        async def main():
            pmap, states, servers, ports = await _two_partition_fleet()
            try:
                users = [f"bu{i}" for i in range(8)]
                provers = {
                    u: Prover(params, Witness(password_to_scalar("pw", u)))
                    for u in users
                }
                eb = Ristretto255.element_to_bytes
                c = AuthClient(partition_map=pmap)
                resp = await c.register_batch(
                    users,
                    [eb(provers[u].statement.y1) for u in users],
                    [eb(provers[u].statement.y2) for u in users],
                )
                assert len(resp.results) == len(users)
                assert all(r.success for r in resp.results), [
                    r.message for r in resp.results
                ]
                # each user landed on its owning partition, none on both
                for u in users:
                    idx = pmap.partition_for(u).index
                    assert u in states[idx]._users
                    assert u not in states[1 - idx]._users

                # full verify_proof_batch fan-out, results in order
                cids, wires = [], []
                for u in users:
                    ch = await c.create_challenge(u)
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    cids.append(cid)
                    wires.append(provers[u].prove_with_transcript(
                        SecureRng(), t).to_bytes())
                vresp = await c.verify_proof_batch(users, cids, wires)
                assert all(r.success for r in vresp.results), [
                    r.message for r in vresp.results
                ]
                assert [r.session_token[:0] for r in vresp.results] == [""] * 8
                await c.close()
            finally:
                for s in servers:
                    await s.stop(None)

        run(main())

    def test_stale_map_client_converges_in_one_redirect(self):
        from cpzk_tpu.client.__main__ import do_login, do_register

        async def main():
            pmap, states, servers, ports = await _two_partition_fleet()
            u1 = uid_on_partition(pmap, 1)
            refreshes = []
            try:
                c = AuthClient(partition_map=pmap)
                assert "Registered" in await do_register(c, u1, "pw")
                await c.close()

                # stale view: one partition, everything at server 0
                stale = PartitionMap.uniform([f"127.0.0.1:{ports[0]}"])

                def refresh():
                    refreshes.append(1)
                    return PartitionMap.from_doc(pmap.to_doc())

                c2 = AuthClient(partition_map=stale, map_refresh=refresh)
                out = await do_login(c2, u1, "pw")
                assert "Login OK" in out, out
                # one redirect per RPC attempt (challenge + verify), each
                # converging in exactly one re-route
                assert c2.redirects <= 2
                assert refreshes  # the bounded refresh actually ran
                await c2.close()
            finally:
                for s in servers:
                    await s.stop(None)

        run(main())

    def test_redirect_charges_the_retry_budget(self):
        from cpzk_tpu.resilience.retry import RetryBudget, RetryPolicy

        async def main():
            pmap, states, servers, ports = await _two_partition_fleet()
            u1 = uid_on_partition(pmap, 1)
            try:
                stale = PartitionMap.uniform([f"127.0.0.1:{ports[0]}"])
                policy = RetryPolicy(budget=RetryBudget(tokens=10.0))
                c = AuthClient(partition_map=stale, retry=policy)
                before = policy.budget.tokens
                with pytest.raises(grpc.aio.AioRpcError):
                    # registration of an unowned user redirects (budget
                    # charged), then the owner rejects the junk wire
                    await c.register(u1, b"\x00", b"\x00")
                assert policy.budget.tokens < before
                assert c.redirects == 1
                await c.close()

                # an exhausted budget refuses the re-route outright
                drained = RetryPolicy(budget=RetryBudget(tokens=1.0))
                drained.budget._tokens = 0.5
                c2 = AuthClient(partition_map=stale, retry=drained)
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await c2.create_challenge(u1)
                assert exc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
                assert c2.redirects == 0
                await c2.close()
            finally:
                for s in servers:
                    await s.stop(None)

        run(main())

    def test_plain_failed_precondition_is_not_a_redirect(self):
        """Only the fleet's own trailer pair triggers a re-route: a bare
        FAILED_PRECONDITION (or one with half the trailers) parses as
        not-a-redirect and surfaces immediately."""
        from cpzk_tpu.client.rpc import _redirect_info

        class FakeErr:
            def __init__(self, md):
                self._md = md

            def trailing_metadata(self):
                return self._md

        assert _redirect_info(FakeErr(())) == (None, None)
        assert _redirect_info(FakeErr((
            ("cpzk-partition-owner", "a:1"),
        ))) == (None, None)
        assert _redirect_info(FakeErr((
            ("cpzk-partition-map-version", "3"),
        ))) == (None, None)
        assert _redirect_info(FakeErr((
            ("cpzk-partition-map-version", "garbage"),
            ("cpzk-partition-owner", "a:1"),
        ))) == (None, None)
        assert _redirect_info(FakeErr((
            ("cpzk-partition-map-version", b"3"),
            ("cpzk-partition-owner", b"a:1"),
        ))) == ("a:1", 3)


# --- the split flow ---------------------------------------------------------


async def _seed_partition(tmp_path, tag: str, users: int):
    """A stopped partition's durable file set with ``users`` registered,
    one session and one challenge mixed in."""
    state = ServerState()
    mgr = DurabilityManager(
        state, DurabilitySettings(enabled=True, fsync="always"),
        str(tmp_path / f"{tag}.json"),
    )
    await mgr.recover()
    for i in range(users):
        await state.register_user(
            UserData(f"user-{i:03d}", make_statement(), 1)
        )
    await state.create_sessions([
        (state.tag_session_token("user-000", "ab" * 32), "user-000"),
    ])
    cid = state.tag_challenge_id("user-001", rng.fill_bytes(32))
    await state.create_challenge("user-001", cid)
    await mgr.close()
    return str(tmp_path / f"{tag}.json")


async def _recovered(state_file: str) -> ServerState:
    from cpzk_tpu.durability.recovery import recover_state

    state = ServerState()
    await recover_state(state, state_file, state_file + ".wal")
    return state


class TestSplit:
    N_USERS = 30

    def _assert_disjoint_exhaustive(self, s0, s1, newmap):
        u0, u1 = set(s0._users), set(s1._users)
        assert not (u0 & u1)
        assert u0 | u1 == {f"user-{i:03d}" for i in range(self.N_USERS)}
        for uid in u0:
            assert newmap.partition_for(uid).index == 0
        for uid in u1:
            assert newmap.partition_for(uid).index == 1

    def test_split_acceptance_disjoint_exhaustive_ownership(self, tmp_path):
        async def main():
            src = await _seed_partition(tmp_path, "p0", self.N_USERS)
            tgt = str(tmp_path / "p1.json")
            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            report = await run_split(
                map_path, 0, "127.0.0.1:2", src, tgt, segment_bytes=512
            )
            assert report["new_version"] == 2
            assert report["segments"] >= 2  # small segments: real splits
            assert report["moved_users"] == report["dropped_users"] > 0
            newmap = PartitionMap.load(map_path)
            assert newmap.version == 2
            s0, s1 = await _recovered(src), await _recovered(tgt)
            self._assert_disjoint_exhaustive(s0, s1, newmap)
            # moved live session/challenge landed with their owners
            sess_owner = newmap.partition_for("user-000").index
            holder = (s0, s1)[sess_owner]
            other = (s1, s0)[sess_owner]
            assert len(holder._sessions) == 1
            assert len(other._sessions) == 0
            # the fencing epoch persisted for the new partition
            from cpzk_tpu.replication import load_epoch

            assert load_epoch(tgt + ".epoch") == report["epoch"] >= 2
            assert not os.path.exists(manifest_path(map_path))

        run(main())

    @pytest.mark.parametrize("point", SPLIT_CRASH_POINTS)
    def test_sigkill_at_any_stage_resumes_consistent(self, tmp_path, point):
        """The chaos guarantee: a split killed at ANY stage leaves both
        partitions' files in a state where (a) serving is already
        non-overlapping (enforcement covers the flipped-but-undrained
        window) and (b) re-running the same command completes to
        disjoint, exhaustive ownership."""

        async def main():
            src = await _seed_partition(tmp_path, "p0", self.N_USERS)
            tgt = str(tmp_path / "p1.json")
            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            plan = FaultPlan().crash_on(point)
            with pytest.raises(CrashPoint):
                await run_split(
                    map_path, 0, "127.0.0.1:2", src, tgt,
                    segment_bytes=512, faults=plan,
                )
            # the kill window is already safe: whatever the map says, at
            # most one partition is authoritative for every user
            mid = PartitionMap.load(map_path)
            assert mid.version in (1, 2)
            # resume with the identical command
            report = await run_split(
                map_path, 0, "127.0.0.1:2", src, tgt, segment_bytes=512
            )
            assert report["new_version"] == 2
            newmap = PartitionMap.load(map_path)
            assert newmap.version == 2
            s0, s1 = await _recovered(src), await _recovered(tgt)
            self._assert_disjoint_exhaustive(s0, s1, newmap)
            assert not os.path.exists(manifest_path(map_path))

        run(main())

    def test_mismatched_resume_manifest_refused(self, tmp_path):
        async def main():
            src = await _seed_partition(tmp_path, "p0", 8)
            tgt = str(tmp_path / "p1.json")
            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            plan = FaultPlan().crash_on("pre_copy")
            with pytest.raises(CrashPoint):
                await run_split(
                    map_path, 0, "127.0.0.1:2", src, tgt, faults=plan
                )
            with pytest.raises(SplitError, match="different split"):
                await run_split(
                    map_path, 0, "127.0.0.1:OTHER", src, tgt
                )

        run(main())

    def test_post_split_fleet_serves_and_stale_client_redirects(
        self, tmp_path
    ):
        """Boot both partitions from the split's files, with routers on
        the new map: every user logs in against the fleet, and a client
        still holding the v1 map converges via one redirect."""
        from cpzk_tpu.client.__main__ import do_login, do_register

        async def main():
            src = await _seed_partition(tmp_path, "p0", 6)
            tgt = str(tmp_path / "p1.json")
            map_path = str(tmp_path / "map.json")
            PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
            await run_split(map_path, 0, "127.0.0.1:2", src, tgt)

            s0, s1 = await _recovered(src), await _recovered(tgt)
            srv0, p0 = await serve(s0, RateLimiter(10**6, 10**6), port=0)
            srv1, p1 = await serve(s1, RateLimiter(10**6, 10**6), port=0)
            # the on-disk map carries placeholder addresses; re-address
            # it to the live ports at the same version (deploy config)
            disk = PartitionMap.load(map_path)
            live = PartitionMap.from_doc({
                "schema": "cpzk-partition-map/1",
                "version": disk.version,
                "partitions": [
                    {"index": 0, "address": f"127.0.0.1:{p0}",
                     "ranges": [list(r) for r in disk.partitions[0].ranges]},
                    {"index": 1, "address": f"127.0.0.1:{p1}",
                     "ranges": [list(r) for r in disk.partitions[1].ranges]},
                ],
            })
            srv0.auth_service.fleet = FleetRouter(live, 0)
            srv1.auth_service.fleet = FleetRouter(live, 1)
            try:
                # a fresh registration + login for a user on each side
                c = AuthClient(partition_map=live)
                for idx in (0, 1):
                    uid = uid_on_partition(live, idx, tag="fresh")
                    assert "Registered" in await do_register(c, uid, "pw")
                    assert "Login OK" in await do_login(c, uid, "pw")
                assert c.redirects == 0
                await c.close()

                # stale-map client: v1 routes everything to partition 0
                moved = uid_on_partition(live, 1, tag="fresh")
                stale = PartitionMap.uniform([f"127.0.0.1:{p0}"])
                c2 = AuthClient(
                    partition_map=stale,
                    map_refresh=lambda: PartitionMap.from_doc(live.to_doc()),
                )
                assert "Login OK" in await do_login(c2, moved, "pw")
                assert 1 <= c2.redirects <= 2  # <= 1 per RPC attempt
                assert c2.partition_map.version == live.version
                await c2.close()
            finally:
                await srv0.stop(None)
                await srv1.stop(None)

        run(main())


# --- proof-log rotation + shipping (PR 9 tail) ------------------------------


class TestAuditRotation:
    def test_rotation_seals_and_resumes_numbering(self, tmp_path):
        from cpzk_tpu.audit.log import (
            ProofLogWriter, proof_record, read_log, sealed_segments,
        )

        path = str(tmp_path / "proofs.log")
        w = ProofLogWriter(path, fsync="off", segment_bytes=512)
        rec = lambda i: proof_record(  # noqa: E731
            f"u{i}", b"\x01" * 32, b"\x02" * 32, b"c" * 32, b"p" * 64, True
        )
        for i in range(40):
            w.append_proofs([rec(i)])
        assert w.rotations >= 2
        segs = sealed_segments(path)
        assert len(segs) == w.rotations
        assert segs == sorted(segs)
        # sealed files parse clean; seqs strictly increase across files
        prev = 0
        for seg in segs:
            records, valid, size = read_log(seg)
            assert valid == size and records
            assert records[0]["seq"] == prev + 1
            prev = records[-1]["seq"]
        st = w.status()
        assert st["rotations_this_boot"] == w.rotations
        assert st["sealed_segments"] == len(segs)
        w.close()
        # a reopened writer resumes numbering past sealed history
        w2 = ProofLogWriter(path, fsync="off", segment_bytes=512)
        assert w2.seq == 40
        w2.append_proofs([rec(99)])
        assert w2.seq == 41
        w2.close()

    def test_directory_replay_equals_single_log_replay(self, tmp_path):
        """A rotated-segment directory audits to the byte-identical
        digest of the same records in one unrotated log."""
        from cpzk_tpu.audit.log import ProofLogWriter, proof_record
        from cpzk_tpu.audit.pipeline import run_audit
        from cpzk_tpu.core.transcript import Transcript

        rot_dir = tmp_path / "rotated"
        rot_dir.mkdir()
        eb = Ristretto255.element_to_bytes
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        payloads = []
        for i in range(24):
            ctx = rng.fill_bytes(32)
            t = Transcript()
            t.append_context(ctx)
            wire = prover.prove_with_transcript(rng, t).to_bytes()
            payloads.append(proof_record(
                f"u{i % 4}", eb(prover.statement.y1),
                eb(prover.statement.y2), ctx, wire, True,
            ))
        rot = ProofLogWriter(
            str(rot_dir / "proofs.log"), fsync="off", segment_bytes=600
        )
        flat = ProofLogWriter(str(tmp_path / "flat.log"), fsync="off")
        for p in payloads:
            rot.append_proofs([dict(p)])
            flat.append_proofs([dict(p)])
        rot.close()
        flat.close()
        assert rot.rotations >= 2

        rep_dir = run_audit(
            str(rot_dir), str(tmp_path / "dir-report.json"), quantum=7
        )
        rep_flat = run_audit(
            str(tmp_path / "flat.log"), str(tmp_path / "flat-report.json"),
            quantum=7,
        )
        assert rep_dir["digest"] == rep_flat["digest"]
        assert rep_dir["totals"] == rep_flat["totals"]
        assert rep_dir["totals"]["verified"] == 24

    def test_sealed_segments_survive_machine_death(self, tmp_path):
        """The PR 9 tail, end to end: a rotating proof log on the
        primary ships sealed segments to the standby; killing the
        primary loses at most the unsealed active tail, and the
        standby's copy replays clean."""
        from cpzk_tpu.audit.log import (
            ProofLogWriter, proof_record, sealed_segments,
        )
        from cpzk_tpu.audit.pipeline import run_audit

        async def main():
            pri = tmp_path / "pri"
            sby = tmp_path / "sby"
            pri.mkdir()
            sby.mkdir()
            w = ProofLogWriter(
                str(pri / "proofs.log"), fsync="off", segment_bytes=512
            )

            sstate = ServerState()
            smgr = DurabilityManager(
                sstate, DurabilitySettings(enabled=True),
                str(sby / "state.json"),
            )
            await smgr.recover()
            replica = StandbyReplica(
                sstate, smgr,
                ReplicationSettings(
                    enabled=True, role="standby", lease_ms=5000,
                    renew_interval_ms=100,
                ),
                audit_path=str(sby / "proofs.log"),
            )
            sserver, sport = await serve(
                sstate, RateLimiter(10**6, 10**6), port=0, replica=replica
            )
            pstate = ServerState()
            pmgr = DurabilityManager(
                pstate, DurabilitySettings(enabled=True),
                str(pri / "state.json"),
            )
            await pmgr.recover()
            shipper = SegmentShipper(
                pstate, pmgr,
                ReplicationSettings(
                    enabled=True, role="primary",
                    peer=f"127.0.0.1:{sport}", lease_ms=5000,
                    renew_interval_ms=30, mode="async",
                ),
                audit_log=w,
            )
            shipper.start()
            try:
                from cpzk_tpu.core.transcript import Transcript

                eb = Ristretto255.element_to_bytes
                prover = Prover(
                    params, Witness(Ristretto255.random_scalar(rng))
                )
                for i in range(30):
                    ctx = rng.fill_bytes(32)
                    t = Transcript()
                    t.append_context(ctx)
                    wire = prover.prove_with_transcript(rng, t).to_bytes()
                    w.append_proofs([proof_record(
                        f"u{i}", eb(prover.statement.y1),
                        eb(prover.statement.y2), ctx, wire, True,
                    )])
                n_sealed = len(w.sealed_segments())
                assert n_sealed >= 2
                await wait_for(
                    lambda: shipper.audit_segments_shipped >= n_sealed
                )
                assert replica.audit_segments_received >= n_sealed
                assert shipper.status()["audit_segments_shipped"] >= n_sealed
                assert (
                    replica.status()["audit_segments_received"] >= n_sealed
                )
                # SIGKILL stand-in: the primary vanishes, unsealed tail
                # and all; the standby's sealed copies are intact and
                # byte-identical...
                await shipper.kill()
                got = sealed_segments(str(sby / "proofs.log"))
                assert len(got) == n_sealed
                for a, b in zip(sorted(w.sealed_segments()), got,
                                strict=True):
                    with open(a, "rb") as fa, open(b, "rb") as fb:
                        assert fa.read() == fb.read()
                # ...and the standby's segment directory audits clean
                report = run_audit(
                    str(sby), str(tmp_path / "sby-report.json"), quantum=8
                )
                assert report["totals"]["skipped"] == 0
                assert report["totals"]["mismatched"] == 0
                assert report["totals"]["audited"] > 0
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)

        run(main())

    def test_stale_epoch_audit_segment_is_fenced(self, tmp_path):
        from cpzk_tpu.audit.log import ProofLogWriter, proof_record

        async def main():
            sby = tmp_path / "sby"
            sby.mkdir()
            sstate = ServerState()
            smgr = DurabilityManager(
                sstate, DurabilitySettings(enabled=True),
                str(sby / "state.json"),
            )
            await smgr.recover()
            replica = StandbyReplica(
                sstate, smgr,
                ReplicationSettings(
                    enabled=True, role="standby", lease_ms=5000,
                    renew_interval_ms=100,
                ),
                audit_path=str(sby / "proofs.log"),
            )
            replica.applier.epoch = 5  # a promotion happened elsewhere
            pb2 = replica.pb2
            req = pb2.ShipSegmentRequest(
                epoch=3, kind="audit", frames=b"junk", crc32=0,
                first_seq=1, last_seq=1,
            )
            resp = await replica.ship_segment(req, None)
            assert not resp.accepted and "fenced" in resp.message
            # a standby without an audit plane refuses rather than drops
            replica.audit_path = None
            req2 = pb2.ShipSegmentRequest(
                epoch=5, kind="audit", frames=b"junk", crc32=0,
                first_seq=1, last_seq=1,
            )
            resp2 = await replica.ship_segment(req2, None)
            assert not resp2.accepted and "no audit plane" in resp2.message

        run(main())


# --- chaos acceptance: 3-partition fleet ------------------------------------


class TestFleetChaos:
    def test_kill_one_partition_others_serve_uninterrupted(self, tmp_path):
        """THE fleet acceptance scenario: partition 0 is a replicated
        pair (sync mode, fsync=always); partitions 1 and 2 are plain
        primaries.  SIGKILL partition 0's primary mid-traffic — its
        standby auto-promotes and completes a pre-crash user's login
        with zero acknowledged loss, while logins against partitions 1
        and 2 NEVER error through the whole window; a stale-map client
        is redirected and completes its login."""
        from cpzk_tpu.client.__main__ import do_login, do_register

        async def main():
            # partition 0: primary + warm standby over real gRPC
            sstate = ServerState()
            smgr = DurabilityManager(
                sstate, DurabilitySettings(enabled=True, fsync="always"),
                str(tmp_path / "p0-standby.json"),
            )
            await smgr.recover()
            replica = StandbyReplica(
                sstate, smgr,
                ReplicationSettings(
                    enabled=True, role="standby", lease_ms=400,
                    renew_interval_ms=40, mode="sync",
                ),
            )
            sserver, sport = await serve(
                sstate, RateLimiter(10**6, 10**6), port=0, replica=replica
            )
            replica.start()

            pstate = ServerState()
            pmgr = DurabilityManager(
                pstate, DurabilitySettings(enabled=True, fsync="always"),
                str(tmp_path / "p0-primary.json"),
            )
            await pmgr.recover()
            shipper = SegmentShipper(
                pstate, pmgr,
                ReplicationSettings(
                    enabled=True, role="primary",
                    peer=f"127.0.0.1:{sport}", lease_ms=400,
                    renew_interval_ms=40, mode="sync",
                ),
            )
            pmgr.attach_shipper(shipper)
            pstate.attach_replication_barrier(shipper.wait_replicated)
            pserver, pport = await serve(
                pstate, RateLimiter(10**6, 10**6), port=0
            )
            shipper.start()

            # partitions 1 and 2: plain primaries
            s1, s2 = ServerState(), ServerState()
            srv1, port1 = await serve(s1, RateLimiter(10**6, 10**6), port=0)
            srv2, port2 = await serve(s2, RateLimiter(10**6, 10**6), port=0)

            pmap = PartitionMap.uniform([
                f"127.0.0.1:{pport}",
                f"127.0.0.1:{port1}",
                f"127.0.0.1:{port2}",
            ])
            pserver.auth_service.fleet = FleetRouter(pmap, 0)
            srv1.auth_service.fleet = FleetRouter(pmap, 1)
            srv2.auth_service.fleet = FleetRouter(pmap, 2)

            u0 = uid_on_partition(pmap, 0)
            # login pools for the surviving partitions: each user mints
            # at most 4 sessions (the server caps at 5 per user), so the
            # traffic loop cycles users instead of tripping the cap
            pools = {
                1: [uid_on_partition(pmap, 1, tag=f"s{k}-") for k in range(5)],
                2: [uid_on_partition(pmap, 2, tag=f"s{k}-") for k in range(5)],
            }
            logins_done: dict[str, int] = {}

            survivor_errors: list[str] = []
            stop_traffic = asyncio.Event()

            async def survivor_traffic():
                c = AuthClient(partition_map=pmap)
                k = 0
                try:
                    while not stop_traffic.is_set():
                        for idx in (1, 2):
                            uid = pools[idx][k % len(pools[idx])]
                            if logins_done.get(uid, 0) >= 4:
                                continue
                            out = await do_login(c, uid, "pw-" + uid)
                            logins_done[uid] = logins_done.get(uid, 0) + 1
                            if "Login OK" not in out:
                                survivor_errors.append(out)
                        k += 1
                        await asyncio.sleep(0.01)
                finally:
                    await c.close()

            try:
                c = AuthClient(partition_map=pmap)
                for uid in [u0] + pools[1] + pools[2]:
                    assert "Registered" in await do_register(
                        c, uid, "pw-" + uid
                    )
                out = await do_login(c, u0, "pw-" + u0)
                assert "Login OK" in out
                await c.close()
                # every acknowledged p0 write is standby-applied (sync)
                assert replica.applied_seq == pmgr.wal.seq

                traffic = asyncio.get_running_loop().create_task(
                    survivor_traffic()
                )
                await asyncio.sleep(0.1)

                # SIGKILL stand-in for partition 0's primary
                await shipper.kill()
                await pserver.stop(None)

                # its standby promotes within the lease window...
                await wait_for(lambda: replica.role == "primary")
                assert replica.epoch == 2

                # ...while the other two partitions served throughout
                await asyncio.sleep(0.2)
                stop_traffic.set()
                await traffic
                assert not survivor_errors, survivor_errors[:3]
                assert sum(logins_done.values()) >= 4  # real coverage

                # the promoted standby serves partition 0's users with
                # zero acknowledged loss (fresh full login)
                async with AuthClient(f"127.0.0.1:{sport}") as c2:
                    assert "Login OK" in await do_login(c2, u0, "pw-" + u0)

                # stale-map client: still routing p0's user at the dead
                # primary's address; the updated map (v2, promoted
                # standby's address) arrives via its refresh hook and
                # the login completes
                promoted = PartitionMap.from_doc({
                    "schema": "cpzk-partition-map/1",
                    "version": 2,
                    "partitions": [
                        {"index": 0, "address": f"127.0.0.1:{sport}",
                         "ranges": [list(r)
                                    for r in pmap.partitions[0].ranges]},
                        {"index": 1, "address": f"127.0.0.1:{port1}",
                         "ranges": [list(r)
                                    for r in pmap.partitions[1].ranges]},
                        {"index": 2, "address": f"127.0.0.1:{port2}",
                         "ranges": [list(r)
                                    for r in pmap.partitions[2].ranges]},
                    ],
                })
                sserver.auth_service.fleet = FleetRouter(promoted, 0)
                srv1.auth_service.fleet = FleetRouter(promoted, 1)
                srv2.auth_service.fleet = FleetRouter(promoted, 2)
                # route a p0 user at partition 1 by handing the stale
                # client a map that owns everything at partition 1
                stale = PartitionMap.uniform([f"127.0.0.1:{port1}"])
                c3 = AuthClient(
                    partition_map=stale, map_refresh=lambda: promoted
                )
                assert "Login OK" in await do_login(c3, u0, "pw-" + u0)
                assert 1 <= c3.redirects <= 2
                assert c3.partition_map.version == 2
                await c3.close()
            finally:
                stop_traffic.set()
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                await srv1.stop(None)
                await srv2.stop(None)

        run(main())


# --- config surface ---------------------------------------------------------


class TestFleetConfig:
    def test_env_layering_and_validation(self, tmp_path, monkeypatch):
        map_path = str(tmp_path / "map.json")
        PartitionMap.uniform(["a:1"]).store(map_path)
        monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "none.toml"))
        monkeypatch.setenv("SERVER_FLEET_ENABLED", "1")
        monkeypatch.setenv("SERVER_FLEET_MAP_PATH", map_path)
        monkeypatch.setenv("SERVER_FLEET_PARTITION", "0")
        monkeypatch.setenv("SERVER_FLEET_ADVERTISE", "a:1")
        cfg = ServerConfig.from_env()
        assert cfg.fleet.enabled is True
        assert cfg.fleet.map_path == map_path
        assert cfg.fleet.partition == 0
        assert cfg.fleet.advertise == "a:1"
        cfg.validate()

        bad = ServerConfig()
        bad.fleet.enabled = True
        with pytest.raises(ValueError, match="map_path"):
            bad.validate()
        bad2 = ServerConfig()
        bad2.fleet.partition = -2
        with pytest.raises(ValueError, match="partition"):
            bad2.validate()
        bad3 = ServerConfig()
        bad3.audit.segment_bytes = -1
        with pytest.raises(ValueError, match="segment_bytes"):
            bad3.validate()

    def test_fleet_config_keys_documented(self):
        """CI drift guard: every [fleet] knob ships in the TOML example,
        the .env example, and the operations-doc knob inventory."""
        keys = [f.name for f in dataclasses.fields(FleetSettings)]
        assert keys

        toml_text = (ROOT / "config" / "server.toml.example").read_text()
        m = re.search(r"^\[fleet\]$", toml_text, re.M)
        assert m, "[fleet] section missing from config/server.toml.example"
        section = toml_text[m.end():].split("\n[", 1)[0]
        env_text = (ROOT / ".env.example").read_text()
        docs = (ROOT / "docs" / "operations.md").read_text()
        for key in keys:
            assert re.search(rf"^{key}\s*=", section, re.M), (
                f"[fleet] key {key!r} missing from config/server.toml.example"
            )
            assert f"SERVER_FLEET_{key.upper()}" in env_text, (
                f"SERVER_FLEET_{key.upper()} missing from .env.example"
            )
            assert f"`fleet.{key}`" in docs, (
                f"`fleet.{key}` missing from the docs/operations.md "
                "knob inventory"
            )

    def test_repl_fleet_command(self, tmp_path):
        from cpzk_tpu.server.__main__ import handle_command

        async def main():
            state = ServerState()
            out, _ = await handle_command("/fleet", state)
            assert "fleet routing disabled" in out

            map_path = str(tmp_path / "map.json")
            v1 = PartitionMap.uniform(["a:1", "b:2"])
            v1.store(map_path)
            router = FleetRouter(v1, 1, map_path=map_path)
            out, _ = await handle_command(
                "/fleet", state, None, None, None, None, None, router
            )
            assert "partition=1/2" in out and "map=v1" in out
            out, _ = await handle_command(
                "/fleet reload", state, None, None, None, None, None, router
            )
            assert "map unchanged" in out
            v2, _ = v1.split(0, "c:3")
            v2.store(map_path)
            out, _ = await handle_command(
                "/fleet reload", state, None, None, None, None, None, router
            )
            assert "map=v2" in out and "partition=1/3" in out

        run(main())

    def test_statusz_and_partitionmap_endpoint(self, tmp_path):
        """The ops plane serves the fleet rollup and the canonical map —
        and the map body round-trips through the client-side validator
        (so map_refresh can point straight at /partitionmap)."""
        import urllib.error
        import urllib.request

        from cpzk_tpu.observability.opsplane import OpsPlane, OpsSources
        from cpzk_tpu.observability.slo import SloEngine
        from cpzk_tpu.server.config import SloSettings

        async def main():
            pmap, _ = PartitionMap.uniform(["a:1", "b:2"]).split(1, "c:3")
            router = FleetRouter(pmap, 2)
            engine = SloEngine(SloSettings())
            engine.partition = "2"
            plane = OpsPlane(
                OpsSources(fleet=router, slo=engine), port=0
            )
            port = await plane.start()

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}"
                ) as r:
                    return json.loads(r.read())

            try:
                statusz = await asyncio.to_thread(get, "/statusz")
                assert statusz["fleet"]["partition"] == 2
                assert statusz["fleet"]["map_version"] == 2
                assert statusz["fleet"]["partitions"] == 3

                doc = await asyncio.to_thread(get, "/partitionmap")
                fetched = PartitionMap.from_doc(doc)
                assert fetched.version == 2
                assert fetched.digest == pmap.digest

                slo = await asyncio.to_thread(get, "/slo")
                assert slo["partition"] == "2"

                # without a fleet source the endpoint 404s with a reason
                bare = OpsPlane(OpsSources(), port=0)
                bport = await bare.start()

                def get_bare():
                    return urllib.request.urlopen(
                        f"http://127.0.0.1:{bport}/partitionmap"
                    ).read()

                try:
                    with pytest.raises(urllib.error.HTTPError) as exc:
                        await asyncio.to_thread(get_bare)
                    assert exc.value.code == 404
                finally:
                    await bare.stop()
            finally:
                await plane.stop()

        run(main())

    def test_fleet_gauges_exported(self):
        FleetRouter(PartitionMap.uniform(["a:1", "b:2"]), 1)
        assert metrics.read("fleet.partition", "g") == 1.0
        assert metrics.read("fleet.map_version", "g") == 1.0


# --- standby addresses: map v2 + client failover dial (ISSUE 18) -------------


class TestStandbyAddresses:
    def test_v2_roundtrip_and_v1_byte_compat(self, tmp_path):
        """A map with standbys serializes as schema /2 and round-trips;
        a standby-free map stays BYTE-identical to v1 (same schema tag,
        same digest) so every pre-upgrade reader keeps working."""
        plain = PartitionMap.uniform(["a:1", "b:2"])
        assert plain.to_doc()["schema"] == "cpzk-partition-map/1"
        assert all(p.standby is None for p in plain.partitions)

        v2 = PartitionMap.uniform(
            ["a:1", "b:2"], standbys=["a:9", None]
        )
        doc = v2.to_doc()
        assert doc["schema"] == "cpzk-partition-map/2"
        assert doc["partitions"][0]["standby"] == "a:9"
        assert "standby" not in doc["partitions"][1]
        path = str(tmp_path / "map.json")
        v2.store(path)
        loaded = PartitionMap.load(path)
        assert loaded.partitions[0].standby == "a:9"
        assert loaded.partitions[1].standby is None
        assert loaded.digest == v2.digest
        # standby-free serialization is digest-stable against v1
        assert (
            plain.to_json()
            == PartitionMap.uniform(["a:1", "b:2"]).to_json()
        )

    def test_set_and_swap_standby(self):
        pmap = PartitionMap.uniform(["a:1", "b:2"])
        with_sb = pmap.set_standby(0, "a:9")
        assert with_sb.version == pmap.version + 1
        assert with_sb.partitions[0].standby == "a:9"
        assert with_sb.partitions[1].standby is None
        cleared = with_sb.set_standby(0, None)
        assert cleared.partitions[0].standby is None

        flipped = with_sb.swap_standby(0)
        assert flipped.version == with_sb.version + 1
        assert flipped.partitions[0].address == "a:9"
        assert flipped.partitions[0].standby == "a:1"
        with pytest.raises(ValueError, match="no standby"):
            with_sb.swap_standby(1)

    def test_split_preserves_standby(self):
        pmap = PartitionMap.uniform(["a:1", "b:2"], standbys=["a:9", "b:9"])
        new_map, _ = pmap.split(0, "c:3")
        assert new_map.partitions[0].standby == "a:9"
        assert new_map.partitions[1].standby == "b:9"
        assert new_map.partitions[2].standby is None  # new partition: none

    def test_v2_rejections(self):
        with pytest.raises(ValueError, match="standbys"):
            PartitionMap.uniform(["a:1", "b:2"], standbys=["a:9"])
        with pytest.raises(ValueError, match="standby"):
            PartitionMap.uniform(["a:1"], standbys=["a:1"])
        doc = PartitionMap.uniform(["a:1"], standbys=["a:9"]).to_doc()
        doc["partitions"][0]["standby"] = 7
        doc.pop("digest")
        with pytest.raises(ValueError, match="standby"):
            PartitionMap.from_doc(doc)

    def test_client_dials_standby_on_unavailable(self):
        """A dead primary answers UNAVAILABLE; a v2-map client dials the
        partition's warm standby once — before any retry budget is
        charged — and the RPC succeeds there."""
        import socket

        from cpzk_tpu.resilience.retry import RetryBudget, RetryPolicy

        async def main():
            state = ServerState()
            server, live = await serve(
                state, RateLimiter(10**6, 10**6), port=0
            )
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            dead = s.getsockname()[1]
            s.close()
            try:
                pmap = PartitionMap.uniform(
                    [f"127.0.0.1:{dead}"],
                    standbys=[f"127.0.0.1:{live}"],
                )
                policy = RetryPolicy(budget=RetryBudget(tokens=10.0))
                c = AuthClient(partition_map=pmap, retry=policy)
                before = policy.budget.tokens
                p = Prover(params, Witness(Ristretto255.random_scalar(rng)))
                eb = Ristretto255.element_to_bytes
                resp = await c.register(
                    "sb-user", eb(p.statement.y1), eb(p.statement.y2)
                )
                assert resp.success, resp.message
                assert c.standby_dials == 1
                assert policy.budget.tokens == before  # free dial
                assert "sb-user" in state._users
                # the flipped orientation routes too: map already names
                # the standby as primary, old primary is down
                flipped = PartitionMap.uniform(
                    [f"127.0.0.1:{live}"],
                    standbys=[f"127.0.0.1:{dead}"],
                )
                c2 = AuthClient(partition_map=flipped)
                ch = await c2.create_challenge("sb-user")
                assert ch.challenge_id
                assert c2.standby_dials == 0  # primary answered directly
                await c.close()
                await c2.close()
            finally:
                await server.stop(None)

        run(main())
