"""Security-test completion (VERDICT r1 item 10): the reference's
identity-statement known-limitation test and proof-size-window sweep
(``tests/security_tests.rs:135-149, 211-237`` analogs), plus pinned
proof-byte vectors so wire-level compatibility is a test, not a claim.
"""

import hashlib
import json
import os

from cpzk_tpu import (
    Error,
    Parameters,
    Proof,
    Prover,
    SecureRng,
    Statement,
    Transcript,
    Verifier,
    Witness,
)
from cpzk_tpu.core.ristretto import Ristretto255, Scalar
from cpzk_tpu.core.scalars import L

VECTORS = os.path.join(os.path.dirname(__file__), "vectors", "proof_vectors.json")


def test_identity_statement_known_limitation():
    """Statement.validate allows the identity pair — parity with the
    reference's documented limitation (security_tests.rs:135-149); the
    *service* registration path is where identity statements are rejected
    (service.rs:93-97 / server.service._parse_statement)."""
    identity = Ristretto255.identity()
    assert Ristretto255.is_identity(identity)
    Statement(identity, identity).validate()  # must NOT raise (parity)


def test_proof_size_window():
    """109-byte proofs sit inside the reference's 32 < len < 1024 window
    (security_tests.rs:211-237)."""
    rng = SecureRng()
    params = Parameters.new()
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    wire = prover.prove_with_transcript(rng, Transcript()).to_bytes()
    assert 32 < len(wire) < 1024
    assert len(wire) == 109  # exact format: 1 + 3*(4 + 32)


def test_pinned_proof_vectors():
    """Deterministic vectors pin the generators, the 109-byte wire format,
    and Merlin challenge derivation: regenerating each proof from its
    SHA-512-derived witness/nonce must reproduce the exact bytes, and
    verification must match the recorded accept bit."""
    with open(VECTORS) as f:
        data = json.load(f)

    params = Parameters.new()
    eb = Ristretto255.element_to_bytes
    assert eb(params.generator_g).hex() == data["generator_g"]
    assert eb(params.generator_h).hex() == data["generator_h"]

    def det_scalar(label: str) -> Scalar:
        h = hashlib.sha512(b"cpzk-tpu-test-vector:" + label.encode()).digest()
        return Scalar(int.from_bytes(h, "little") % L)

    for i, vec in enumerate(v for v in data["vectors"] if v["accept"]):
        x = det_scalar(f"witness-{i}")
        k = det_scalar(f"nonce-{i}")
        ctx = bytes.fromhex(vec["context"]) if vec["context"] else None

        y1 = Ristretto255.scalar_mul(params.generator_g, x)
        y2 = Ristretto255.scalar_mul(params.generator_h, x)
        assert eb(y1).hex() == vec["y1"] and eb(y2).hex() == vec["y2"]

        r1 = Ristretto255.scalar_mul(params.generator_g, k)
        r2 = Ristretto255.scalar_mul(params.generator_h, k)
        t = Transcript()
        if ctx is not None:
            t.append_context(ctx)
        t.append_parameters(eb(params.generator_g), eb(params.generator_h))
        t.append_statement(eb(y1), eb(y2))
        t.append_commitment(eb(r1), eb(r2))
        c = t.challenge_scalar()
        assert Ristretto255.scalar_to_bytes(c).hex() == vec["challenge"]

        s = Scalar((k.value + c.value * x.value) % L)
        from cpzk_tpu.protocol.gadgets import Commitment
        from cpzk_tpu.protocol.prover import Response

        wire = Proof(Commitment(r1, r2), Response(s)).to_bytes()
        assert wire.hex() == vec["proof"], f"wire drift in {vec['name']}"

        vt = Transcript()
        if ctx is not None:
            vt.append_context(ctx)
        Verifier(params, Statement(y1, y2)).verify_with_transcript(
            Proof.from_bytes(wire), vt
        )

    # rejection vectors: recorded proof must NOT verify under its context
    for vec in (v for v in data["vectors"] if not v["accept"]):
        proof = Proof.from_bytes(bytes.fromhex(vec["proof"]))
        y1 = Ristretto255.element_from_bytes(bytes.fromhex(vec["y1"]))
        y2 = Ristretto255.element_from_bytes(bytes.fromhex(vec["y2"]))
        vt = Transcript()
        vt.append_context(bytes.fromhex(vec["context"]))
        try:
            Verifier(params, Statement(y1, y2)).verify_with_transcript(proof, vt)
            raise AssertionError(f"{vec['name']} unexpectedly verified")
        except Error:
            pass
