"""Dispatch-lane tests (the batcher->backend seam rearchitecture):
FIFO ordering, leak-free drain-then-join shutdown, chaos containment
(backend raise mid-batch with and without the failover wrapper), the
double-buffering proof obligations (near-zero dispatch gap on a
synthetic slow-host workload, ``device_wait`` staging accounting,
sub-millisecond ``thread_hop``), AOT prewarm (zero steady-state compile
spans after warmup), and the ``[tpu] prewarm_quanta`` config knob.
"""

import asyncio
import json
import time

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.observability import get_flight_recorder
from cpzk_tpu.ops import backend as backend_mod
from cpzk_tpu.ops.backend import TpuBackend, prewarm_executables
from cpzk_tpu.protocol.batch import (
    BatchEntry,
    CpuBackend,
    FailoverBackend,
    VerifierBackend,
)
from cpzk_tpu.server.batching import DynamicBatcher
from cpzk_tpu.server.dispatch import DispatchLane, LaneStopped


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _fresh_recorder():
    rec = get_flight_recorder()
    rec.clear()
    yield
    rec.clear()


def make_entries(n, params=None, rng=None):
    rng = rng or SecureRng()
    params = params or Parameters.new()
    out = []
    for i in range(n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        ctx = b"lane-%04d" % i
        t = Transcript()
        t.append_context(ctx)
        proof = prover.prove_with_transcript(rng, t)
        out.append(BatchEntry(params, prover.statement, proof, ctx))
    return out


class RecordingBackend(VerifierBackend):
    """CPU oracle wrapper that logs every backend call's batch size."""

    prefers_combined = False

    def __init__(self, delay_s: float = 0.0):
        self.sizes: list[int] = []
        self.delay_s = delay_s
        self._inner = CpuBackend()

    def verify_combined(self, rows, beta):  # pragma: no cover - unused
        raise AssertionError("prefers_combined is False")

    def verify_each(self, rows):
        self.sizes.append(len(rows))
        if self.delay_s:
            time.sleep(self.delay_s)
        return self._inner.verify_each(rows)


class ExplodingBackend(VerifierBackend):
    prefers_combined = False

    def __init__(self, explode_times: int = 10**9):
        self.calls = 0
        self.explode_times = explode_times

    def verify_combined(self, rows, beta):  # pragma: no cover - unused
        raise AssertionError("prefers_combined is False")

    def verify_each(self, rows):
        self.calls += 1
        if self.calls <= self.explode_times:
            raise RuntimeError("injected device loss")
        return [True] * len(rows)


# --- ordering / fairness -----------------------------------------------------


def test_lane_executes_batches_fifo():
    """Submission order IS execution order: the MPSC ingress and the
    staging buffer are both FIFO, so no batch can overtake another."""
    backend = RecordingBackend()
    sizes = [2, 3, 4, 5, 2, 3]

    async def main():
        lane = DispatchLane(backend, overlap=True)
        lane.start()
        futs = [lane.submit(make_entries(k), None) for k in sizes]
        results = await asyncio.gather(*futs)
        await lane.stop()
        return results

    results = run(main())
    assert [len(r) for r in results] == sizes
    assert all(all(e is None for e in r) for r in results)
    assert backend.sizes == sizes  # FIFO, nothing reordered or coalesced


def test_lane_serial_mode():
    """overlap=False (pipeline_depth=1) runs both phases on one
    persistent thread — same results, strictly serial."""
    backend = RecordingBackend()

    async def main():
        lane = DispatchLane(backend, overlap=False)
        lane.start()
        futs = [lane.submit(make_entries(2), None) for _ in range(3)]
        results = await asyncio.gather(*futs)
        await lane.stop()
        return results

    results = run(main())
    assert [len(r) for r in results] == [2, 2, 2]
    assert backend.sizes == [2, 2, 2]


# --- shutdown ----------------------------------------------------------------


def test_lane_stop_drains_in_flight_batches():
    """stop() refuses new work but DRAINS accepted batches: every future
    resolves with real results, and submit-after-stop raises."""
    backend = RecordingBackend(delay_s=0.05)

    async def main():
        lane = DispatchLane(backend, overlap=True)
        lane.start()
        futs = [lane.submit(make_entries(2), None) for _ in range(4)]
        stop_task = asyncio.ensure_future(lane.stop())
        await asyncio.sleep(0)  # let stop() flip the accepting flag
        with pytest.raises(LaneStopped):
            lane.submit(make_entries(1), None)
        await stop_task
        assert all(f.done() for f in futs), "stop() returned before drain"
        return await asyncio.gather(*futs)

    results = run(main())
    assert len(results) == 4
    assert all(r == [None, None] for r in results)
    assert backend.sizes == [2, 2, 2, 2]


def test_lane_futures_never_leak_on_cancel():
    """A cancelled result future (RPC gave up) neither blocks the lane
    nor errors it: later batches still verify, and stop() stays clean."""
    backend = RecordingBackend(delay_s=0.02)

    async def main():
        lane = DispatchLane(backend, overlap=True)
        lane.start()
        doomed = lane.submit(make_entries(2), None)
        live = lane.submit(make_entries(3), None)
        doomed.cancel()
        result = await live
        await lane.stop()
        return doomed, result

    doomed, result = run(main())
    assert doomed.cancelled()
    assert result == [None] * 3
    assert backend.sizes == [2, 3]  # the cancelled batch still verified


def test_batcher_stop_resolves_every_pending_future():
    """Acceptance: stopping the server with in-flight batches resolves
    every pending entry future — none left pending, none leaked."""
    backend = RecordingBackend(delay_s=0.03)
    entries = make_entries(6)

    async def main():
        batcher = DynamicBatcher(backend, max_batch=2, window_ms=1.0)
        batcher.start()
        pending = [
            asyncio.ensure_future(batcher.submit_many([e])) for e in entries
        ]
        await asyncio.sleep(0.02)  # let some batches commit to the lane
        await batcher.stop()
        done = [f.done() for f in pending]
        results = await asyncio.gather(*pending)
        return done, results

    done, results = run(main())
    assert all(done), "batcher.stop() returned with unresolved futures"
    assert results == [[None]] * 6


# --- chaos -------------------------------------------------------------------


def test_lane_contains_backend_explosion_to_its_batch():
    """A backend raise mid-batch resolves THAT batch's future with the
    exception; the lane threads survive and serve the next batch."""
    backend = ExplodingBackend(explode_times=1)

    async def main():
        lane = DispatchLane(backend, overlap=True)
        lane.start()
        first = lane.submit(make_entries(2), None)
        with pytest.raises(RuntimeError, match="injected device loss"):
            await first
        second = await lane.submit(make_entries(2), None)
        await lane.stop()
        return second

    assert run(main()) == [None, None]


def test_lane_failover_breaker_engages_through_lane():
    """With the failover wrapper, a device loss on the lane's device
    thread degrades to the CPU fallback mid-stream: results stay
    correct and the breaker records the trip — the resilience machinery
    is orthogonal to WHERE the dispatch runs."""
    broken = ExplodingBackend()
    backend = FailoverBackend(broken, CpuBackend())

    async def main():
        batcher = DynamicBatcher(backend, max_batch=8, window_ms=2.0)
        batcher.start()
        results = await batcher.submit_many(make_entries(4))
        await batcher.stop()
        return results

    assert run(main()) == [None] * 4
    assert backend.degraded
    assert broken.calls == 1  # breaker opened on the first loss


# --- double-buffering proof obligations --------------------------------------


def test_double_buffered_dispatch_gap_near_zero(tmp_path):
    """Synthetic slow-host workload: device time dominates host prep, so
    with double-buffering the device thread never idles between batches
    — steady-state dispatch gap must clamp toward 0 (ISSUE acceptance),
    and the staged batches book their dwell as ``device_wait``.  Also
    exercises the ring dump while the lane threads are the writers (the
    SIGUSR2 path's thread-safety)."""
    backend = RecordingBackend(delay_s=0.06)  # "device" >> host prep

    async def main():
        batcher = DynamicBatcher(
            backend, max_batch=2, window_ms=1.0, pipeline_depth=2
        )
        batcher.start()
        waves = [make_entries(2) for _ in range(4)]
        results = await asyncio.gather(
            *[batcher.submit_many(w) for w in waves]
        )
        await batcher.stop()
        return results

    results = run(main())
    assert all(r == [None, None] for r in results)
    records = get_flight_recorder().snapshot()
    assert len(records) == 4
    steady = records[1:]  # first batch has no predecessor to overlap
    for rec in steady:
        # device held ~60ms per batch; an un-overlapped pipeline would
        # show ~prep-sized gaps — overlap clamps them to scheduler noise
        assert rec.dispatch_gap_s < 0.03, rec.to_dict()
    assert any(
        r.stages_s.get("device_wait", 0.0) > 0.0 for r in steady
    ), [r.to_dict() for r in records]
    # the ring dump works while lane threads were the writers
    path = tmp_path / "ring.json"
    get_flight_recorder().dump(str(path))
    assert len(json.loads(path.read_text())["records"]) == 4


def test_thread_hop_is_condition_variable_cheap():
    """The per-batch thread_hop is a cv wakeup on a hot persistent
    thread, not a thread-pool handoff: sub-millisecond in the common
    case (asserted loosely at 50ms p50 for CI noise; the real number
    lands in the perf snapshot's stage percentiles)."""
    backend = RecordingBackend()

    async def main():
        batcher = DynamicBatcher(backend, max_batch=4, window_ms=1.0)
        batcher.start()
        for _ in range(5):
            await batcher.submit_many(make_entries(2))
        await batcher.stop()

    run(main())
    records = get_flight_recorder().snapshot()
    hops = sorted(r.stages_s.get("thread_hop", 0.0) for r in records)
    assert len(hops) == 5
    assert hops[len(hops) // 2] < 0.05
    # stage-sum ≈ wall keeps holding with the lane vocabulary
    for rec in records:
        # tiny batches leave microsecond slivers between marks; the strict
        # rel-only form is pinned on >=64-entry batches in test_flightrec
        assert rec.stage_sum_s() == pytest.approx(
            rec.wall_s, rel=0.10, abs=2.5e-3
        ), rec.to_dict()


def test_stopped_batcher_inline_path_same_seam():
    """The stopped-batcher inline verify rides the SAME dispatch seam
    (DispatchLane.verify_once): the flight record still lands with the
    full stage decomposition and the stage-sum invariant intact."""
    backend = RecordingBackend()

    async def main():
        batcher = DynamicBatcher(backend, max_batch=8, window_ms=1.0)
        # never started: submit_many falls to the inline seam
        return await batcher.submit_many(make_entries(3))

    assert run(main()) == [None] * 3
    records = get_flight_recorder().snapshot()
    assert len(records) == 1
    rec = records[0]
    assert rec.stages_s.get("thread_hop", 0.0) >= 0.0
    assert rec.stages_s.get("execute", 0.0) > 0.0
    assert rec.stage_sum_s() == pytest.approx(rec.wall_s, rel=0.10, abs=2.5e-3)


# --- AOT prewarm -------------------------------------------------------------


def test_prewarm_then_zero_compile_spans(monkeypatch):
    """ISSUE acceptance: after prewarm, the FIRST serving dispatch at a
    warmed quantum books jit cache hits only — zero ``compile`` spans,
    all device time attributed to ``execute``."""
    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    monkeypatch.setattr(backend_mod, "_AOT_CACHE", {})
    warmed = prewarm_executables([6])
    # combined pads 6+1 -> 8 lanes; the verify_each fallback pads 6 -> 8
    assert set(warmed) == {"combined/8", "each/8/True"}
    assert prewarm_executables([6]) == []  # idempotent per shape

    async def main():
        batcher = DynamicBatcher(TpuBackend(), max_batch=16, window_ms=1.0)
        batcher.start()
        results = await batcher.submit_many(make_entries(6))
        await batcher.stop()
        return results

    assert run(main()) == [None] * 6
    records = get_flight_recorder().snapshot()
    assert len(records) == 1
    rec = records[0]
    assert rec.jit_misses == 0, rec.to_dict()
    assert rec.jit_hits > 0
    assert rec.stages_s.get("compile", 0.0) == 0.0
    assert rec.stages_s.get("execute", 0.0) > 0.0
    assert rec.lanes == 8


def test_prewarm_aot_path_is_bit_correct(monkeypatch):
    """The AOT executable path must agree with the oracle — including
    the combined-check failure falling back to the (also warmed)
    verify_each kernel flagging the bad row."""
    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    monkeypatch.setattr(backend_mod, "_AOT_CACHE", {})
    prewarm_executables([5])
    rng = SecureRng()
    params = Parameters.new()
    entries = make_entries(5, params=params, rng=rng)
    # corrupt one entry: statement/proof mismatch
    other = make_entries(1, params=params, rng=rng)[0]
    entries[2] = BatchEntry(
        params, other.statement, entries[2].proof,
        entries[2].transcript_context,
    )

    from cpzk_tpu.protocol.batch import BatchVerifier

    bv = BatchVerifier(backend=TpuBackend(), max_size=8)
    bv.entries.extend(entries)
    results = bv.verify(rng)
    assert [r is None for r in results] == [True, True, False, True, True]


# --- config knob -------------------------------------------------------------


def test_prewarm_quanta_config_env_and_validation(monkeypatch):
    from cpzk_tpu.server import ServerConfig

    monkeypatch.setenv("SERVER_TPU_PREWARM_QUANTA", "16, 4096")
    cfg = ServerConfig()
    cfg._merge_env()
    assert cfg.tpu.prewarm_quanta == "16, 4096"
    assert cfg.tpu.parsed_prewarm_quanta() == [16, 4096]
    cfg.validate()

    cfg = ServerConfig()
    cfg.tpu.prewarm_quanta = "banana"
    with pytest.raises(ValueError, match="prewarm_quanta"):
        cfg.validate()
    cfg = ServerConfig()
    cfg.tpu.prewarm_quanta = "0,16"
    with pytest.raises(ValueError, match="positive"):
        cfg.validate()
    cfg = ServerConfig()
    cfg.tpu.prewarm_quanta = ""
    assert cfg.tpu.parsed_prewarm_quanta() == []
    cfg.validate()


# --- buffer donation ---------------------------------------------------------


def test_donated_kernels_stay_bit_correct(monkeypatch):
    """CPZK_DONATE_BUFFERS=1 rebuilds the jitted kernels with donated
    per-batch inputs; on the XLA CPU backend donation is ignored (with a
    jax warning) but dispatch must stay bit-correct — the buffer policy
    can never change accept/reject semantics."""
    monkeypatch.setenv("CPZK_DONATE_BUFFERS", "1")
    monkeypatch.setattr(backend_mod, "_KERNELS", {})
    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    monkeypatch.setattr(backend_mod, "_AOT_CACHE", {})
    rng = SecureRng()
    params = Parameters.new()
    entries = make_entries(4, params=params, rng=rng)
    other = make_entries(1, params=params, rng=rng)[0]
    entries[1] = BatchEntry(
        params, other.statement, entries[1].proof,
        entries[1].transcript_context,
    )

    from cpzk_tpu.protocol.batch import BatchVerifier

    bv = BatchVerifier(backend=TpuBackend(), max_size=8)
    bv.entries.extend(entries)
    results = bv.verify(rng)
    assert [r is None for r in results] == [True, False, True, True]


def test_enable_donation_switch(monkeypatch):
    """The serving-daemon switch flips the policy and rebuilds kernels;
    env forcing wins over it in both directions."""
    monkeypatch.setattr(backend_mod, "_KERNELS", {})
    monkeypatch.setattr(backend_mod, "_DONATE_OVERRIDE", None)
    monkeypatch.delenv("CPZK_DONATE_BUFFERS", raising=False)
    assert backend_mod._donation_enabled() is False  # default: off
    backend_mod.enable_donation(True)
    assert backend_mod._donation_enabled() is True
    monkeypatch.setenv("CPZK_DONATE_BUFFERS", "0")
    assert backend_mod._donation_enabled() is False  # env force wins
    backend_mod.enable_donation(False)
    monkeypatch.setenv("CPZK_DONATE_BUFFERS", "1")
    assert backend_mod._donation_enabled() is True


# --- deferred-splice path keeps the full stage decomposition -----------------


def test_splice_path_flight_record_tiles_wall(monkeypatch):
    """A deferred-parse batch with an undecodable wire takes the
    screen-and-splice path; its flight record must still carry the full
    stage decomposition (pad_and_pack covers screening + sub prep, the
    sub-batch's device phase records into the same recorder) and tile
    the wall — the invariant holds on EVERY path, not just the happy
    one."""
    from cpzk_tpu.protocol.gadgets import Proof

    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    monkeypatch.setattr(backend_mod, "_AOT_CACHE", {})
    rng = SecureRng()
    params = Parameters.new()
    entries = make_entries(6, params=params, rng=rng)
    # re-parse one proof deferred, then corrupt a commitment point wire
    wire = entries[2].proof.to_bytes()
    bad_wire = wire[:5] + b"\xff" * 32 + wire[37:]
    bad, = Proof.from_bytes_batch([bad_wire], defer_point_validation=True)
    if not isinstance(bad, Proof):
        pytest.skip("native frame path absent: bad wire fails eagerly")
    entries[2] = BatchEntry(
        params, entries[2].statement, bad, entries[2].transcript_context,
    )

    async def main():
        batcher = DynamicBatcher(TpuBackend(), max_batch=16, window_ms=1.0)
        batcher.start()
        results = await batcher.submit_many(entries)
        await batcher.stop()
        return results

    results = run(main())
    assert [r is None for r in results] == [
        True, True, False, True, True, True,
    ]
    rec = get_flight_recorder().snapshot()[-1]
    assert rec.stages_s.get("pad_and_pack", 0.0) > 0.0, rec.to_dict()
    assert rec.stages_s.get("execute", 0.0) + rec.stages_s.get(
        "compile", 0.0) > 0.0, rec.to_dict()
    assert rec.jit_hits + rec.jit_misses > 0, rec.to_dict()
    assert rec.stage_sum_s() == pytest.approx(
        rec.wall_s, rel=0.10, abs=2.5e-3
    ), rec.to_dict()
