"""Large-batch coverage (VERDICT r1 item 4): configurable batch ceiling,
the 100-user gRPC batch (reference ``batch_verification_tests.rs:396-460``
twin), and an env-gated 64k-row device batch for TPU runs.
"""

import asyncio
import os

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.errors import InvalidParams
from cpzk_tpu.protocol.batch import MAX_BATCH_SIZE, BatchVerifier
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server.service import serve


def test_batch_ceiling_configurable():
    rng = SecureRng()
    params = Parameters.new()
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    proof = prover.prove_with_transcript(rng, Transcript())

    # reference-parity default
    assert BatchVerifier().max_size == MAX_BATCH_SIZE == 1000

    small = BatchVerifier(max_size=2)
    small.add(params, prover.statement, proof)
    small.add(params, prover.statement, proof)
    assert small.remaining_capacity() == 0
    with pytest.raises(InvalidParams):
        small.add(params, prover.statement, proof)

    big = BatchVerifier(max_size=100_000)
    assert big.remaining_capacity() == 100_000
    with pytest.raises(InvalidParams):
        BatchVerifier(max_size=0)


def test_100_user_grpc_batch():
    """100 users register (batch RPC), get challenges, and batch-login —
    the reference's largest integration scenario."""

    async def main():
        rng = SecureRng()
        params = Parameters.new()
        state = ServerState()
        server, port = await serve(
            state, RateLimiter(100_000, 100_000), host="127.0.0.1", port=0
        )
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = [f"load{i:03d}" for i in range(100)]
                provers = {
                    u: Prover(params, Witness(Ristretto255.random_scalar(rng)))
                    for u in users
                }
                reg = await client.register_batch(
                    users,
                    [
                        Ristretto255.element_to_bytes(provers[u].statement.y1)
                        for u in users
                    ],
                    [
                        Ristretto255.element_to_bytes(provers[u].statement.y2)
                        for u in users
                    ],
                )
                assert len(reg.results) == 100 and all(r.success for r in reg.results)

                challenge_ids, proofs = [], []
                for u in users:
                    ch = await client.create_challenge(u)
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    proofs.append(
                        provers[u].prove_with_transcript(rng, t).to_bytes()
                    )
                    challenge_ids.append(cid)

                resp = await client.verify_proof_batch(users, challenge_ids, proofs)
                assert len(resp.results) == 100
                assert all(r.success and r.session_token for r in resp.results)
                assert await state.session_count() == 100
        finally:
            await server.stop(None)

    asyncio.run(main())


@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("CPZK_SLOW_TESTS"),
    reason="64k-row device batch: minutes of XLA compile on CPU; set "
    "CPZK_SLOW_TESTS=1 (CI slow tier / TPU runs)",
)
def test_64k_row_device_batch():
    """64k rows through TpuBackend's Pippenger combined check + one
    corrupted row falling back to per-proof results (SURVEY.md §7.5)."""
    from cpzk_tpu.ops.backend import TpuBackend

    rng = SecureRng()
    params = Parameters.new()
    corpus = []
    for _ in range(16):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        corpus.append((prover.statement, prover.prove_with_transcript(rng, Transcript())))

    n = 65_536
    bv = BatchVerifier(backend=TpuBackend(), max_size=n)
    for i in range(n):
        st, pr = corpus[i % len(corpus)]
        bv.add(params, st, pr)
    assert bv.verify(rng) == [None] * n

    bad = BatchVerifier(backend=TpuBackend(), max_size=n)
    for i in range(n - 1):
        st, pr = corpus[i % len(corpus)]
        bad.add(params, st, pr)
    bad.add(params, corpus[0][0], corpus[1][1])  # mismatched
    results = bad.verify(rng)
    assert results[-1] is not None
    assert all(r is None for r in results[:-1])
