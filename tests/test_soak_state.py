"""Million-user state plane (ISSUE 14): streaming snapshots, time-wheel
expiry, churn-leak pins, maintained counters, and the scaled-down soak
smoke.

The tier-1 face of what ``benches/bench_soak.py`` measures at 1M users:

- the per-user-list churn leak is dead (maps return to their pre-churn
  size after every session/challenge is revoked/consumed);
- the maintained global counters never drift from the map truth;
- a sweep's cost scales with the EXPIRED count, not the live count
  (operation-counting spy over ``last_sweep_stats``), and the journaled
  one-timestamp ``expire_sessions`` record still replays to exactly the
  removed set;
- the streaming per-shard snapshot is byte-identical to the old
  monolithic ``json.dump`` document, restores equivalently, and the
  early WAL watermark stays safe under replay idempotency;
- the 20k-user smoke: snapshot pause bounded, sweep examines nothing
  when nothing is due, RSS sanity.
"""

import asyncio
import json

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.durability import DurabilityManager
from cpzk_tpu.durability.wal import read_frames
from cpzk_tpu.server import metrics
from cpzk_tpu.server.config import DurabilitySettings
from cpzk_tpu.server.state import (
    EXPIRY_WHEEL_GRANULARITY_S,
    SESSION_EXPIRY_SECONDS,
    ChallengeData,
    ServerState,
    SessionData,
    UserData,
)

rng = SecureRng()
params = Parameters.new()


def run(coro):
    return asyncio.run(coro)


def make_statement():
    return Prover(params, Witness(Ristretto255.random_scalar(rng))).statement


SHARED_STMT = make_statement()  # state size matters here, not keygen


async def register_many(state, n, stmt=None):
    for i in range(n):
        await state.register_user(
            UserData(f"u{i}", stmt or SHARED_STMT, 1)
        )


def map_sizes(state):
    return {
        "users": sum(len(s._users) for s in state._shards),
        "sessions": sum(len(s._sessions) for s in state._shards),
        "challenges": sum(len(s._challenges) for s in state._shards),
        "user_sessions": sum(len(s._user_sessions) for s in state._shards),
        "user_challenges": sum(
            len(s._user_challenges) for s in state._shards
        ),
        "session_wheel": sum(
            len(b) for s in state._shards for b in s._session_wheel.values()
        ),
        "challenge_wheel": sum(
            len(b) for s in state._shards
            for b in s._challenge_wheel.values()
        ),
    }


def assert_counters_exact(state):
    """The maintained counters ARE the map truth (funnel integrity)."""
    assert state._total_users() == sum(
        len(s._users) for s in state._shards
    )
    assert state._total_sessions() == sum(
        len(s._sessions) for s in state._shards
    )
    assert state._total_challenges() == sum(
        len(s._challenges) for s in state._shards
    )


# --- churn leak (satellite 1) ------------------------------------------------


def test_churn_returns_maps_to_pre_churn_size():
    """revoke/consume used to leave the emptied per-user list entries
    behind forever — the dicts grew with every user that ever held a
    session.  Pin: after full churn the index maps are back to their
    pre-churn size, and the wheels are empty too."""

    async def main():
        state = ServerState()
        await register_many(state, 200)
        before = map_sizes(state)
        assert before["user_sessions"] == 0
        for round_ in range(3):
            for i in range(200):
                tok = state.tag_session_token(
                    f"u{i}", f"{round_:02d}{i:038d}"[:40]
                )
                await state.create_session(tok, f"u{i}")
                cid = state.tag_challenge_id(f"u{i}", bytes([0] * 32))
                cid = bytes([cid[0], round_, i % 256]) + cid[3:]
                await state.create_challenge(f"u{i}", cid)
            assert state._total_sessions() == 200
            for i in range(200):
                tok = state.tag_session_token(
                    f"u{i}", f"{round_:02d}{i:038d}"[:40]
                )
                await state.revoke_session(tok)
                cid = state.tag_challenge_id(f"u{i}", bytes([0] * 32))
                cid = bytes([cid[0], round_, i % 256]) + cid[3:]
                await state.consume_challenge(cid)
            after = map_sizes(state)
            assert after == before, f"round {round_}: churn leaked {after}"
            assert_counters_exact(state)

    run(main())


def test_sweep_churn_also_deletes_emptied_lists():
    async def main():
        state = ServerState()
        await register_many(state, 50)
        for i in range(50):
            state._sessions[f"dead{i}"] = SessionData(
                token=f"dead{i}", user_id=f"u{i}",
                created_at=1, expires_at=2,
            )
            state._user_sessions.setdefault(f"u{i}", []).append(f"dead{i}")
        assert await state.cleanup_expired_sessions() == 50
        assert map_sizes(state)["user_sessions"] == 0
        assert_counters_exact(state)

    run(main())


# --- maintained counters (satellite 2) --------------------------------------


def test_counters_track_view_writes_and_deletes():
    async def main():
        state = ServerState()
        await register_many(state, 10)
        state._sessions["viewtok"] = SessionData(
            token="viewtok", user_id="u1"
        )
        assert state._total_sessions() == 1
        # replace (same key) must not double-count
        state._sessions["viewtok"] = SessionData(
            token="viewtok", user_id="u1"
        )
        assert state._total_sessions() == 1
        del state._sessions["viewtok"]
        assert state._total_sessions() == 0
        state._challenges[b"c" * 32] = ChallengeData(
            challenge_id=b"c" * 32, user_id="u2"
        )
        assert state._total_challenges() == 1
        del state._challenges[b"c" * 32]
        assert state._total_challenges() == 0
        assert_counters_exact(state)
        with pytest.raises(KeyError):
            del state._sessions["viewtok"]

    run(main())


def test_caps_enforced_exactly_through_counters():
    async def main():
        state = ServerState(max_users=5, max_sessions=3, max_challenges=2)
        for i in range(5):
            await state.register_user(UserData(f"u{i}", SHARED_STMT, 1))
        from cpzk_tpu.errors import InvalidParams

        with pytest.raises(InvalidParams, match="maximum user capacity"):
            await state.register_user(UserData("u5", SHARED_STMT, 1))
        out = await state.create_sessions(
            [(state.tag_session_token(f"u{i}", f"{i:040d}"), f"u{i}")
             for i in range(4)]
        )
        # bulk mint processes in shard-index order, so WHICH entry hits
        # the cap depends on hashing — exactly one must, three succeed
        assert out.count(None) == 3
        rejected = [m for m in out if m is not None]
        assert len(rejected) == 1
        assert "maximum session capacity (3)" in rejected[0]
        for i in range(2):
            await state.create_challenge(
                f"u{i}", state.tag_challenge_id(f"u{i}", bytes([i]) * 32)
            )
        with pytest.raises(InvalidParams, match="maximum challenge capacity"):
            await state.create_challenge(
                "u3", state.tag_challenge_id("u3", b"x" * 32)
            )

    run(main())


# --- time-wheel expiry (tentpole c) ------------------------------------------


def test_sweep_examines_expired_not_live():
    """The operation-counting spy: 5000 live sessions cost the sweep
    NOTHING (no due buckets), and 40 expired ones cost O(40)."""

    async def main():
        state = ServerState()
        await register_many(state, 100)
        pairs = [
            (state.tag_session_token(f"u{i % 100}", f"{i:040d}"),
             f"u{i % 100}")
            for i in range(500)
        ]
        out = await state.create_sessions(pairs)
        assert all(m is None for m in out)
        removed = await state.cleanup_expired_sessions()
        assert removed == 0
        examined, removed_, _dur = state.last_sweep_stats["sessions"]
        assert examined == 0, (
            f"sweep examined {examined} entries with nothing due — "
            "the wheel is not bounding sweep cost"
        )
        # now 40 expired entries among the 500 live
        for i in range(40):
            state._sessions[f"exp{i}"] = SessionData(
                token=f"exp{i}", user_id=f"u{i}",
                created_at=10, expires_at=20,
            )
        removed = await state.cleanup_expired_sessions()
        assert removed == 40
        examined, removed_, _dur = state.last_sweep_stats["sessions"]
        assert removed_ == 40
        assert examined <= 80, (
            f"sweep examined {examined} entries for 40 expired — "
            "cost is not O(expired)"
        )
        assert_counters_exact(state)

    run(main())


def test_challenge_sweep_examines_expired_not_live():
    async def main():
        state = ServerState()
        await register_many(state, 100)
        for i in range(300):
            await state.create_challenge(
                f"u{i % 100}",
                state.tag_challenge_id(
                    f"u{i % 100}", bytes([i % 256, i // 256]) + b"c" * 30
                ),
            )
        assert await state.cleanup_expired_challenges() == 0
        assert state.last_sweep_stats["challenges"][0] == 0
        for i in range(25):
            cid = bytes([255, i]) + b"e" * 30
            state._challenges[cid] = ChallengeData(
                challenge_id=cid, user_id=f"u{i}",
                created_at=10, expires_at=20,
            )
        assert await state.cleanup_expired_challenges() == 25
        examined = state.last_sweep_stats["challenges"][0]
        assert examined <= 50
        assert_counters_exact(state)

    run(main())


def test_wheel_handles_clock_skew_guard_bucket():
    """An entry whose 2x-age guard fires before its expires_at must be
    bucketed by the EARLIER instant — otherwise the sweep would miss
    what ``is_expired`` already rejects."""

    async def main():
        state = ServerState()
        await register_many(state, 1)
        # expires_at far future, but created long ago: the age guard
        # (created + 2*TTL) is what expires it
        skewed = SessionData(
            token="skew", user_id="u0",
            created_at=100,
            expires_at=100 + 100 * SESSION_EXPIRY_SECONDS,
        )
        state._sessions["skew"] = skewed
        assert skewed.is_expired()  # the guard has long since fired
        assert await state.cleanup_expired_sessions() == 1
        assert "skew" not in state._sessions

    run(main())


def test_sweep_journal_replay_equivalence(tmp_path):
    """The one-timestamp ``expire_sessions`` record still replays to
    exactly the removed set with the wheel-driven chunked sweep: a
    journal holding aged create_session records plus the sweep's expire
    record rebuilds the post-sweep state."""

    async def main():
        state = ServerState()
        mgr = DurabilityManager(
            state, DurabilitySettings(enabled=True),
            str(tmp_path / "s.json"),
        )
        await mgr.recover()
        await register_many(state, 30)
        # 30 aged sessions: journaled create records with old timestamps
        # (what a long-lived server's WAL really holds), mirrored into
        # the live maps
        for i in range(30):
            tok = f"old{i:037d}"
            mgr.wal.append("create_session", {
                "token": tok, "user_id": f"u{i}",
                "created_at": 10,
                "expires_at": 10 + SESSION_EXPIRY_SECONDS,
            })
            state._sessions[tok] = SessionData(
                token=tok, user_id=f"u{i}", created_at=10,
                expires_at=10 + SESSION_EXPIRY_SECONDS,
            )
        # 10 live ones through the ordinary journaled path
        for i in range(10):
            await state.create_session(
                state.tag_session_token(f"u{i}", f"b{i:039d}"), f"u{i}"
            )
        removed = await state.cleanup_expired_sessions()
        assert removed == 30
        live = sorted(t for s in state._shards for t in s._sessions)
        assert len(live) == 10
        mgr.wal.close()

        # replay the whole journal into a fresh state: identical final set
        records = read_frames(mgr.wal_path)[0]
        assert any(r["type"] == "expire_sessions" for r in records)
        state2 = ServerState()
        for rec in records:
            state2.replay_journal_record(rec)
        live2 = sorted(t for s in state2._shards for t in s._sessions)
        assert live2 == live
        assert_counters_exact(state2)

    run(main())


def test_chunked_sweep_survives_interleaved_mutations(monkeypatch):
    """Bounded lock holds mean mutations interleave mid-sweep; the sweep
    must neither crash nor remove live entries."""

    async def main():
        from cpzk_tpu.server import state as state_mod

        monkeypatch.setattr(state_mod, "SWEEP_CHUNK", 16)
        state = ServerState(shards=2)
        await register_many(state, 8)
        for i in range(200):
            state._sessions[f"old{i}"] = SessionData(
                token=f"old{i}", user_id=f"u{i % 8}",
                created_at=10, expires_at=20,
            )

        minted = []

        async def mutator():
            for i in range(40):
                tok = state.tag_session_token(f"u{i % 8}", f"m{i:039d}")
                await state.create_session(tok, f"u{i % 8}")
                minted.append(tok)
                await asyncio.sleep(0)

        sweep_task = asyncio.ensure_future(
            state.cleanup_expired_sessions()
        )
        await mutator()
        removed = await sweep_task
        assert removed == 200
        for tok in minted:
            assert await state.validate_session(tok)
        assert_counters_exact(state)

    run(main())


# --- streaming snapshot (tentpole b) -----------------------------------------


def monolithic_doc(state, wal_seq=None):
    """The exact document the pre-streaming writer json.dump'ed."""
    eb = Ristretto255.element_to_bytes
    doc = {
        "version": state.SNAPSHOT_VERSION,
        "users": {
            uid: {
                "y1": eb(u.statement.y1).hex(),
                "y2": eb(u.statement.y2).hex(),
                "registered_at": u.registered_at,
            }
            for shard in state._shards
            for uid, u in shard._users.items()
        },
        "sessions": [
            {
                "token": s.token,
                "user_id": s.user_id,
                "created_at": s.created_at,
                "expires_at": s.expires_at,
            }
            for shard in state._shards
            for s in shard._sessions.values()
            if not s.is_expired()
        ],
    }
    if wal_seq is not None:
        doc["wal_seq"] = wal_seq
    return doc


def test_streaming_snapshot_byte_identical_to_monolithic(tmp_path):
    async def main():
        state = ServerState()
        await register_many(state, 64, make_statement())
        for i in range(40):
            await state.create_session(
                state.tag_session_token(f"u{i}", f"{i:040d}"), f"u{i}"
            )
        # an expired session must be filtered out, both ways
        state._sessions["dead"] = SessionData(
            token="dead", user_id="u0", created_at=1, expires_at=2
        )
        expected = json.dumps(monolithic_doc(state))
        path = str(tmp_path / "snap.json")
        assert await state.snapshot(path) is True
        with open(path) as f:
            got = f.read()
        assert got == expected, "streaming writer diverged from json.dump"

        # restore-equivalence
        state2 = ServerState()
        nu, ns = await state2.restore(path)
        assert (nu, ns) == (64, 40)
        assert_counters_exact(state2)

    run(main())


def test_streaming_snapshot_with_wal_seq_byte_identical(tmp_path):
    async def main():
        state = ServerState()
        mgr = DurabilityManager(
            state, DurabilitySettings(enabled=True),
            str(tmp_path / "s.json"),
        )
        await mgr.recover()
        await register_many(state, 8, make_statement())
        await state.create_session(
            state.tag_session_token("u0", "0" * 40), "u0"
        )
        expected = json.dumps(monolithic_doc(state, wal_seq=mgr.wal.seq))
        path = str(tmp_path / "snap.json")
        assert await state.snapshot(path) is True
        with open(path) as f:
            assert f.read() == expected
        mgr.wal.close()

    run(main())


def test_snapshot_cuts_per_shard_and_yields(tmp_path):
    """Structure pin: one pause observation per shard lands in the
    ``state.snapshot.pause_ms`` histogram, and a concurrently scheduled
    task gets the loop between cuts."""

    async def main():
        state = ServerState()
        await register_many(state, 256)
        base_count, _ = metrics.read_histogram("state.snapshot.pause_ms")
        ticks = []

        async def ticker():
            while True:
                ticks.append(1)
                await asyncio.sleep(0)

        t = asyncio.ensure_future(ticker())
        before = len(ticks)
        assert await state.snapshot(str(tmp_path / "s.json")) is True
        during = len(ticks) - before
        t.cancel()
        count, _ = metrics.read_histogram("state.snapshot.pause_ms")
        assert count - base_count == state.num_shards
        assert during >= state.num_shards, (
            f"ticker ran {during} times during the snapshot — the cut "
            "is not yielding between shards"
        )
        assert state.snapshot_max_pause_ms >= 0.0

    run(main())


def test_early_watermark_replay_idempotency(tmp_path):
    """The streaming cut captures the WAL watermark BEFORE the shards:
    a snapshot may therefore contain mutations whose records sit past
    ``wal_seq``.  Restore + suffix replay must converge — duplicated
    creates skip, revokes of absent entries no-op."""

    async def main():
        state = ServerState()
        mgr = DurabilityManager(
            state, DurabilitySettings(enabled=True),
            str(tmp_path / "s.json"),
        )
        await mgr.recover()
        await register_many(state, 4, make_statement())
        tok = state.tag_session_token("u0", "a" * 40)
        await state.create_session(tok, "u0")
        watermark = mgr.wal.seq - 1  # pretend the cut preceded the mint

        # craft the worst-case document by hand: session present in the
        # snapshot, its create record PAST the embedded watermark
        doc = monolithic_doc(state, wal_seq=watermark)
        path = str(tmp_path / "crafted.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        mgr.wal.close()

        state2 = ServerState()
        mgr2 = DurabilityManager(
            state2, DurabilitySettings(enabled=True),
            str(tmp_path / "crafted.json"),
        )
        mgr2.wal_path = mgr.wal_path
        report = await mgr2.recover()
        # the duplicated create was SKIPPED, not applied twice
        assert report.skipped >= 1
        assert await state2.validate_session(tok) == "u0"
        assert state2._total_sessions() == 1
        assert_counters_exact(state2)

        # and the reverse shape: revoked-after-watermark -> the session
        # is absent from the doc, the revoke record replays as a no-op
        await state2.revoke_session(tok)
        assert state2._total_sessions() == 0

    run(main())


# --- scaled-down soak smoke (satellite 3) ------------------------------------


def test_soak_smoke_20k_users(tmp_path):
    """The tier-1 slice of the 1M soak: 20k registered users + 20k live
    sessions; the streaming snapshot's longest synchronous cut stays
    bounded, the sweep examines nothing when nothing is due, and RSS
    stays sane."""

    def vm_rss_mb() -> float:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1]) / 1024.0
        except OSError:
            pass
        return 0.0

    async def main():
        users = 20_000
        rss_before = vm_rss_mb()
        state = ServerState(
            max_users=users * 2, max_sessions=users * 2,
            max_challenges=users,
        )
        await register_many(state, users)
        pairs = [
            (state.tag_session_token(f"u{i}", f"{i:040d}"), f"u{i}")
            for i in range(users)
        ]
        for lo in range(0, users, 2000):
            out = await state.create_sessions(pairs[lo:lo + 2000])
            assert all(m is None for m in out)
        assert state._total_sessions() == users

        # snapshot pause: per-shard reference copies, not serialization
        path = str(tmp_path / "snap.json")
        assert await state.snapshot(path) is True
        assert state.snapshot_max_pause_ms < 250.0, (
            f"snapshot cut paused the loop {state.snapshot_max_pause_ms}ms "
            "at 20k users — the streaming cut is not streaming"
        )

        # sweep: all live, nothing due -> zero entries examined
        assert await state.cleanup_expired_sessions() == 0
        assert state.last_sweep_stats["sessions"][0] == 0
        assert await state.cleanup_expired_challenges() == 0
        assert state.last_sweep_stats["challenges"][0] == 0

        # restore-equivalence at size
        state2 = ServerState(max_users=users * 2, max_sessions=users * 2)
        nu, ns = await state2.restore(path)
        assert (nu, ns) == (users, users)
        assert_counters_exact(state2)

        # RSS sanity: holding 20k users + 20k sessions costs a bounded
        # slice of memory (the 1M-user number is BENCH_SOAK.json's).
        # Delta of CURRENT VmRSS, not process peak — a shared pytest
        # process has already peaked on unrelated suites.
        grew_mb = vm_rss_mb() - rss_before
        assert grew_mb < 1024, f"state build grew RSS {grew_mb:.0f} MB"

    run(main())


# --- wheel bucket math --------------------------------------------------------


def test_wheel_granularity_covers_expiry_exactly():
    """Entries land in the bucket of their effective expiry instant:
    everything in a bucket strictly below ``now // G`` is expired."""
    from cpzk_tpu.server.state import (
        _challenge_wheel_key,
        _session_wheel_key,
    )

    s = SessionData(token="t", user_id="u", created_at=1000,
                    expires_at=1000 + SESSION_EXPIRY_SECONDS)
    k = _session_wheel_key(s)
    bucket_end = (k + 1) * EXPIRY_WHEEL_GRANULARITY_S
    assert s.is_expired(bucket_end)
    assert not s.is_expired(k * EXPIRY_WHEEL_GRANULARITY_S - 1)

    c = ChallengeData(challenge_id=b"c" * 32, user_id="u",
                      created_at=50, expires_at=10_000_000)
    k = _challenge_wheel_key(c)  # the 2x-age guard dominates
    assert c.is_expired((k + 1) * EXPIRY_WHEEL_GRANULARITY_S)
