"""Differential tests: TPU batch proof generation (fixed-base comb kernel)
vs the host prover/verifier (VERDICT r1 missing item 8; BASELINE config 3;
reference analog ``src/prover/mod.rs:115-131``).
"""

import pytest

from cpzk_tpu import (
    Parameters,
    Proof,
    SecureRng,
    Statement,
    Transcript,
    Verifier,
    Witness,
)
from cpzk_tpu.core.ristretto import Ristretto255


@pytest.fixture(scope="module")
def bp():
    from cpzk_tpu.ops.prove import BatchProver

    return BatchProver(Parameters.new())


def test_statements_match_host(bp):
    rng = SecureRng()
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(5)]
    got = bp.statements(witnesses)
    for w, (y1b, y2b) in zip(witnesses, got):
        st = Statement.from_witness(bp.params, Witness(w))
        assert y1b == Ristretto255.element_to_bytes(st.y1)
        assert y2b == Ristretto255.element_to_bytes(st.y2)


def test_batch_proofs_verify(bp):
    rng = SecureRng()
    n = 6
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(n)]
    contexts = [None, b"ctx-1", b"ctx-2", None, b"ctx-4", b"ctx-5"]
    statements, proofs = bp.prove(witnesses, contexts, rng)

    for w, ctx, (y1b, y2b), wire in zip(witnesses, contexts, statements, proofs):
        assert len(wire) == 109
        proof = Proof.from_bytes(wire)  # full adversarial parser accepts
        st = Statement(
            Ristretto255.element_from_bytes(y1b),
            Ristretto255.element_from_bytes(y2b),
        )
        t = Transcript()
        if ctx is not None:
            t.append_context(ctx)
        Verifier(bp.params, st).verify_with_transcript(proof, t)

    # context binding: proof i must not verify under context j
    proof0 = Proof.from_bytes(proofs[0])
    st1 = Statement(
        Ristretto255.element_from_bytes(statements[0][0]),
        Ristretto255.element_from_bytes(statements[0][1]),
    )
    t = Transcript()
    t.append_context(b"ctx-1")
    from cpzk_tpu import Error

    with pytest.raises(Error):
        Verifier(bp.params, st1).verify_with_transcript(proof0, t)


def test_sharded_batch_prove_matches_single_device(bp):
    """DP-sharded proving (mesh over the virtual 8-CPU devices): identical
    commitment/statement bytes to the single-device kernel for the same
    scalars — the proving-side analog of the sharded verify paths."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device (virtual) mesh")
    from cpzk_tpu.ops.prove import BatchProver

    rng = SecureRng()
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(11)]  # ragged
    sharded = BatchProver(Parameters.new(), mesh_devices=0)
    assert sharded._sharded is not None
    assert sharded.statements(witnesses) == bp.statements(witnesses)

    # full prove on the sharded instance verifies under the host verifier
    statements, proofs = sharded.prove(witnesses, None, rng)
    for (y1b, y2b), wire in zip(statements, proofs):
        st = Statement(
            Ristretto255.element_from_bytes(y1b),
            Ristretto255.element_from_bytes(y2b),
        )
        Verifier(sharded.params, st).verify_with_transcript(
            Proof.from_bytes(wire), Transcript()
        )


def test_precomputed_statements_path(bp):
    rng = SecureRng()
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(3)]
    statements = bp.statements(witnesses)
    st2, proofs = bp.prove(witnesses, None, rng, statements=statements)
    assert st2 == statements
    for (y1b, y2b), wire in zip(st2, proofs):
        st = Statement(
            Ristretto255.element_from_bytes(y1b),
            Ristretto255.element_from_bytes(y2b),
        )
        Verifier(bp.params, st).verify_with_transcript(
            Proof.from_bytes(wire), Transcript()
        )
