"""gRPC integration tests — reference ``tests/integration_tests.rs`` twins.

Fixture boots a real asyncio gRPC server on a loopback OS-assigned port
(the reference's fake-backend stand-in, SURVEY.md §4) and drives it with
the hand-wired AuthClient.
"""

import asyncio

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.client.kdf import password_to_scalar
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server.service import serve

import grpc


@pytest.fixture()
def anyio_backend():
    return "asyncio"


async def start_test_server(rate: int = 10_000, burst: int = 10_000):
    state = ServerState()
    server, port = await serve(state, RateLimiter(rate, burst), host="127.0.0.1", port=0)
    return state, server, port


def run(coro):
    return asyncio.run(coro)


def register_and_login_flow(user: str, password: str):
    async def flow():
        _, server, port = await start_test_server()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                # register (reference flow: derive statement from password)
                x = password_to_scalar(password, user)
                params = Parameters.new()
                prover = Prover(params, Witness(x))
                st = prover.statement
                resp = await client.register(
                    user,
                    Ristretto255.element_to_bytes(st.y1),
                    Ristretto255.element_to_bytes(st.y2),
                )
                assert resp.success

                # challenge -> prove with challenge-id context -> verify
                ch = await client.create_challenge(user)
                assert len(ch.challenge_id) == 32
                t = Transcript()
                t.append_context(bytes(ch.challenge_id))
                proof = prover.prove_with_transcript(SecureRng(), t)
                v = await client.verify_proof(user, bytes(ch.challenge_id), proof.to_bytes())
                assert v.success
                assert v.session_token and len(v.session_token) == 64
                return True
        finally:
            await server.stop(None)

    assert run(flow())


def test_full_auth_flow():
    register_and_login_flow("alice", "correct-horse")


def test_duplicate_registration():
    async def flow():
        _, server, port = await start_test_server()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                rng = SecureRng()
                prover = Prover(Parameters.new(), Witness(Ristretto255.random_scalar(rng)))
                y1 = Ristretto255.element_to_bytes(prover.statement.y1)
                y2 = Ristretto255.element_to_bytes(prover.statement.y2)
                assert (await client.register("bob", y1, y2)).success
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await client.register("bob", y1, y2)
                assert exc.value.code() == grpc.StatusCode.ALREADY_EXISTS
        finally:
            await server.stop(None)

    run(flow())


def test_challenge_single_use():
    async def flow():
        _, server, port = await start_test_server()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                rng = SecureRng()
                prover = Prover(Parameters.new(), Witness(Ristretto255.random_scalar(rng)))
                await client.register(
                    "carol",
                    Ristretto255.element_to_bytes(prover.statement.y1),
                    Ristretto255.element_to_bytes(prover.statement.y2),
                )
                ch = await client.create_challenge("carol")
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                proof = prover.prove_with_transcript(rng, t)
                assert (await client.verify_proof("carol", cid, proof.to_bytes())).success
                # replay: challenge consumed -> PERMISSION_DENIED, opaque message
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await client.verify_proof("carol", cid, proof.to_bytes())
                assert exc.value.code() == grpc.StatusCode.PERMISSION_DENIED
                assert exc.value.details() == "Authentication failed"
        finally:
            await server.stop(None)

    run(flow())


def test_wrong_password_rejected():
    async def flow():
        _, server, port = await start_test_server()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                right = Prover(Parameters.new(), Witness(password_to_scalar("pw", "dave")))
                await client.register(
                    "dave",
                    Ristretto255.element_to_bytes(right.statement.y1),
                    Ristretto255.element_to_bytes(right.statement.y2),
                )
                wrong = Prover(Parameters.new(), Witness(password_to_scalar("bad", "dave")))
                ch = await client.create_challenge("dave")
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                proof = wrong.prove_with_transcript(SecureRng(), t)
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await client.verify_proof("dave", cid, proof.to_bytes())
                assert exc.value.code() == grpc.StatusCode.PERMISSION_DENIED
        finally:
            await server.stop(None)

    run(flow())


def test_max_three_challenges():
    async def flow():
        _, server, port = await start_test_server()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                rng = SecureRng()
                prover = Prover(Parameters.new(), Witness(Ristretto255.random_scalar(rng)))
                await client.register(
                    "erin",
                    Ristretto255.element_to_bytes(prover.statement.y1),
                    Ristretto255.element_to_bytes(prover.statement.y2),
                )
                for _ in range(3):
                    await client.create_challenge("erin")
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await client.create_challenge("erin")
                assert exc.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        finally:
            await server.stop(None)

    run(flow())


def test_health_endpoint():
    async def flow():
        _, server, port = await start_test_server()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                resp = await client.health_check()
                assert resp.status == 1  # SERVING
                server.health.serving = False
                resp = await client.health_check()
                assert resp.status == 2  # NOT_SERVING
        finally:
            await server.stop(None)

    run(flow())
