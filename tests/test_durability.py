"""Crash-consistent durability: WAL + bounded-loss recovery (ISSUE 3).

The acceptance contract, exercised deterministically: with durability
enabled, a crash injected at every defined crash point (and a real
``SIGKILL`` — marked ``slow``) followed by a reboot recovers exactly the
acknowledged prefix — every mutation acknowledged before the crash is
present, no partially-written record is applied, and a corrupt
snapshot/WAL quarantines and boots instead of crash-looping.
"""

import asyncio
import dataclasses
import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.durability import (
    DurabilityManager,
    WriteAheadLog,
    encode_record,
    iter_frames,
    read_frames,
)
from cpzk_tpu.resilience.faults import WAL_CRASH_POINTS, CrashPoint, FaultPlan
from cpzk_tpu.server import metrics
from cpzk_tpu.server.config import DurabilitySettings, ServerConfig
from cpzk_tpu.server.state import (
    SESSION_EXPIRY_SECONDS,
    ServerState,
    SessionData,
    UserData,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent

rng = SecureRng()
params = Parameters.new()


def run(coro):
    return asyncio.run(coro)


def make_statement():
    return Prover(params, Witness(Ristretto255.random_scalar(rng))).statement


def make_manager(tmp_path, plan=None, **settings):
    state = ServerState()
    cfg = DurabilitySettings(enabled=True, **settings)
    mgr = DurabilityManager(state, cfg, str(tmp_path / "state.json"), faults=plan)
    return state, mgr


async def register(state, i, stmt=None):
    await state.register_user(
        UserData(f"u{i}", stmt if stmt is not None else make_statement(), 100 + i)
    )


# --- WAL unit behavior ------------------------------------------------------


def test_wal_frame_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "log.wal")
    wal = WriteAheadLog(path, fsync="always")
    s1 = wal.append("register_user", {"user_id": "a"})
    s2 = wal.append("create_session", {"token": "t"})
    assert (s1, s2) == (1, 2)
    wal.close()
    assert os.stat(path).st_mode & 0o777 == 0o600

    records, valid, total = read_frames(path)
    assert valid == total == os.path.getsize(path)
    assert [r["type"] for r in records] == ["register_user", "create_session"]
    assert [r["seq"] for r in records] == [1, 2]

    raw = open(path, "rb").read()
    # torn tail: any strict prefix of the last frame parses to one record
    frame1_end = len(encode_record(records[0]))
    for cut in (frame1_end + 1, frame1_end + 7, len(raw) - 1):
        got, v = iter_frames(raw[:cut])
        assert [r["seq"] for r in got] == [1]
        assert v == frame1_end
    # bit flip inside the second payload: CRC stops the reader there
    flipped = bytearray(raw)
    flipped[frame1_end + 12] ^= 0x40
    got, v = iter_frames(bytes(flipped))
    assert [r["seq"] for r in got] == [1] and v == frame1_end
    # non-increasing seq is corruption, not a record
    dup = raw + encode_record({"seq": 2, "type": "register_user"})
    got, v = iter_frames(dup)
    assert [r["seq"] for r in got] == [1, 2] and v == len(raw)


def test_wal_fsync_policies(tmp_path):
    always = WriteAheadLog(str(tmp_path / "a.wal"), fsync="always")
    base = metrics.read("state.wal.fsyncs")
    always.append("register_user", {})
    assert always.needs_sync() and always.sync() is True
    assert metrics.read("state.wal.fsyncs") == base + 1
    assert always.needs_sync() is False  # nothing pending
    always.close()

    off = WriteAheadLog(str(tmp_path / "b.wal"), fsync="off")
    off.append("register_user", {})
    assert off.needs_sync() is False and off.sync() is False
    assert off.sync(force=True) is True  # shutdown still flushes
    off.close()

    iv = WriteAheadLog(
        str(tmp_path / "c.wal"), fsync="interval", fsync_interval_ms=10_000.0
    )
    iv.append("register_user", {})
    assert iv.needs_sync() is False  # interval not elapsed
    assert iv.sync() is False and iv.pending == 1
    iv._last_fsync -= 11.0  # age the clock past the interval
    assert iv.needs_sync() is True and iv.sync() is True
    iv.close()

    with pytest.raises(ValueError, match="fsync policy"):
        WriteAheadLog(str(tmp_path / "d.wal"), fsync="sometimes")


def test_journal_logs_every_acknowledged_mutation(tmp_path):
    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        await register(state, 0)
        await state.create_session("tok", "u0")
        await state.revoke_session("tok")
        await state.create_session("tok2", "u0")
        # inject an expired session so the sweep journals its record
        state._sessions["dead"] = SessionData(
            token="dead", user_id="u0", created_at=1, expires_at=2
        )
        state._user_sessions.setdefault("u0", []).append("dead")
        assert await state.cleanup_expired_sessions() == 1
        mgr.wal.close()
        return read_frames(mgr.wal_path)[0]

    records = run(main())
    assert [r["type"] for r in records] == [
        "register_user", "create_session", "revoke_session",
        "create_session", "expire_sessions",
    ]
    assert [r["seq"] for r in records] == [1, 2, 3, 4, 5]
    sess = records[1]
    assert sess["token"] == "tok" and sess["user_id"] == "u0"
    assert sess["expires_at"] - sess["created_at"] == SESSION_EXPIRY_SECONDS


# --- recovery ---------------------------------------------------------------


def test_recovery_replays_only_suffix_beyond_snapshot(tmp_path):
    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        stmts = {i: make_statement() for i in range(4)}
        for i in range(2):
            await register(state, i, stmts[i])
        assert await mgr.checkpoint() is True  # snapshot covers seq 1-2
        for i in range(2, 4):
            await register(state, i, stmts[i])
        await state.create_session("tok", "u3")
        # crash without a further snapshot; reboot into a fresh state
        base = metrics.read("state.recovery.replayed")
        state2, mgr2 = make_manager(tmp_path)
        report = await mgr2.recover()
        assert report.snapshot_loaded and (report.users, report.sessions) == (2, 0)
        assert report.covered_seq == 2 and report.replayed == 3
        assert report.skipped == 0 and report.truncated_bytes == 0
        assert metrics.read("state.recovery.replayed") == base + 3
        assert await state2.user_count() == 4
        for i in range(4):
            u = await state2.get_user(f"u{i}")
            assert u is not None and u.statement == stmts[i]
        assert await state2.validate_session("tok") == "u3"
        # the snapshot doc itself records the covered sequence number
        assert json.load(open(mgr2.state_file))["wal_seq"] == 2

    run(main())


@pytest.mark.parametrize("point", ["pre_append", "mid_frame", "post_append_pre_fsync"])
def test_crash_point_recovers_exactly_the_acknowledged_prefix(tmp_path, point):
    """The tentpole acceptance: a crash at every append-side crash point
    reboots to all acknowledged writes and never a torn record."""
    acked = 3  # registrations acknowledged before the crash

    async def main():
        plan = FaultPlan().crash_on(point, occurrence=acked)
        state, mgr = make_manager(tmp_path, plan=plan)
        await mgr.recover()
        for i in range(acked):
            await register(state, i)
        with pytest.raises(CrashPoint):
            await register(state, acked)

        state2, mgr2 = make_manager(tmp_path)
        report = await mgr2.recover()
        for i in range(acked):
            assert await state2.get_user(f"u{i}") is not None
        if point == "mid_frame":
            # the torn frame was truncated away, byte-exactly
            assert report.truncated_bytes > 0
            assert await state2.get_user(f"u{acked}") is None
            assert os.path.getsize(mgr2.wal_path) == read_frames(mgr2.wal_path)[1]
        elif point == "pre_append":
            assert report.truncated_bytes == 0
            assert await state2.get_user(f"u{acked}") is None
        else:  # post_append_pre_fsync: full frame on disk, never fsynced.
            # In-process the page cache survives, so the unacknowledged
            # write MAY appear — allowed (only loss of acked writes and
            # application of torn records are contract violations).
            assert report.truncated_bytes == 0
        # the reopened log accepts appends and a clean reboot sees them
        await register(state2, 90)
        state3, mgr3 = make_manager(tmp_path)
        await mgr3.recover()
        assert await state3.get_user("u90") is not None

    run(main())


def test_crash_pre_rename_leaves_compaction_all_or_nothing(tmp_path):
    async def main():
        plan = FaultPlan().crash_on("pre_rename", occurrence=0)
        state, mgr = make_manager(tmp_path, plan=plan, compact_bytes=0)
        await mgr.recover()
        for i in range(3):
            await register(state, i)
        size_before = mgr.wal.size
        with pytest.raises(CrashPoint):
            await mgr.checkpoint()  # snapshot lands, compaction rename dies
        assert os.path.getsize(mgr.wal_path) == size_before  # old log intact
        # reboot: snapshot + (uncompacted) WAL still recover everything
        state2, mgr2 = make_manager(tmp_path, compact_bytes=0)
        await mgr2.recover()
        assert await state2.user_count() == 3
        # and an unfaulted checkpoint compacts for real
        await register(state2, 3)
        await mgr2.checkpoint()
        assert os.path.getsize(mgr2.wal_path) == 0
        state3, mgr3 = make_manager(tmp_path)
        report = await mgr3.recover()
        assert await state3.user_count() == 4 and report.replayed == 0

    run(main())


def test_compaction_triggers_past_size_threshold(tmp_path):
    async def main():
        state, mgr = make_manager(tmp_path, compact_bytes=10_000)
        await mgr.recover()
        for i in range(4):
            await register(state, i)
        await mgr.checkpoint()
        # covered, but under the threshold: nothing compacted
        assert mgr.wal.size > 0
        for i in range(4, 60):
            await register(state, i)
        assert mgr.wal.size > 10_000
        await mgr.checkpoint()  # now past the threshold -> compact
        # everything the snapshot covers is gone; nothing was appended
        # after the snapshot, so the log is empty
        assert mgr.wal.size == 0
        state2, mgr2 = make_manager(tmp_path)
        report = await mgr2.recover()
        assert await state2.user_count() == 60 and report.replayed == 0

    run(main())


def test_shutdown_close_truncates_covered_wal(tmp_path):
    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        for i in range(5):
            await register(state, i)
        assert mgr.wal.size > 0
        await mgr.close()  # drain -> final snapshot -> truncate
        assert os.path.getsize(mgr.wal_path) == 0
        with pytest.raises(OSError, match="closed"):
            mgr.wal.append("register_user", {})
        state2, mgr2 = make_manager(tmp_path)
        report = await mgr2.recover()
        assert await state2.user_count() == 5
        assert report.replayed == 0  # snapshot covers everything

    run(main())


# --- quarantine paths -------------------------------------------------------


def test_unreadable_wal_quarantined_boots_from_snapshot(tmp_path):
    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        for i in range(3):
            await register(state, i)
        await mgr.checkpoint()
        mgr.wal.close()
        # clobber the log from byte 0: not a torn tail, garbage outright
        with open(mgr.wal_path, "wb") as f:
            f.write(b"\xff" * 64)
        state2, mgr2 = make_manager(tmp_path)
        report = await mgr2.recover()
        assert report.wal_quarantined is not None
        assert os.path.exists(report.wal_quarantined)
        assert ".corrupt-" in report.wal_quarantined
        assert os.stat(report.wal_quarantined).st_mode & 0o777 == 0o600
        assert await state2.user_count() == 3  # snapshot carried the day
        await register(state2, 3)  # fresh log accepts writes
        assert read_frames(mgr2.wal_path)[0][0]["type"] == "register_user"

    run(main())


def test_corrupt_snapshot_quarantined_boots_from_wal(tmp_path):
    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        for i in range(3):
            await register(state, i)
        await state.create_session("tok", "u1")
        await mgr.checkpoint()
        mgr.wal.close()
        # tamper the snapshot; the full (uncompacted) WAL remains good
        doc = open(mgr.state_file).read()
        with open(mgr.state_file, "w") as f:
            f.write(doc[: len(doc) // 2])
        state2, mgr2 = make_manager(tmp_path)
        report = await mgr2.recover()
        assert report.snapshot_quarantined is not None
        assert not report.snapshot_loaded
        assert re.search(r"\.corrupt-\d+", report.snapshot_quarantined)
        assert os.stat(report.snapshot_quarantined).st_mode & 0o777 == 0o600
        # the WAL alone rebuilt the whole acknowledged state
        assert report.replayed == 4
        assert await state2.user_count() == 3
        assert await state2.validate_session("tok") == "u1"

    run(main())


def test_corrupt_snapshot_without_durability_quarantines_not_crashloops(
    tmp_path, monkeypatch
):
    """Satellite: the plain --state-file boot path must quarantine a
    snapshot that fails restore() instead of dying on every restart."""
    from cpzk_tpu.server.__main__ import load_state

    monkeypatch.chdir(tmp_path)  # no stray config/server.toml pickup
    path = tmp_path / "state.json"
    path.write_text('{"version": 1, "users": {"bad user!": ')
    os.chmod(path, 0o600)
    cfg = ServerConfig()
    cfg.state_file = str(path)

    async def main():
        state, durability = await load_state(cfg)
        assert durability is None
        assert await state.user_count() == 0  # booted empty, not crashed
        assert not path.exists()  # moved aside
        corrupt = [p for p in tmp_path.iterdir() if ".corrupt-" in p.name]
        assert len(corrupt) == 1
        assert os.stat(corrupt[0]).st_mode & 0o777 == 0o600

    run(main())


def test_load_state_with_durability_end_to_end(tmp_path, monkeypatch):
    """amain's boot path: recover, write a fresh covering snapshot."""
    from cpzk_tpu.server.__main__ import load_state

    monkeypatch.chdir(tmp_path)
    cfg = ServerConfig()
    cfg.state_file = str(tmp_path / "state.json")
    cfg.durability.enabled = True
    cfg.validate()

    async def main():
        state, durability = await load_state(cfg)
        assert durability is not None and durability.wal is not None
        await register(state, 0)
        # crash (no shutdown); second boot replays the WAL...
        state2, durability2 = await load_state(cfg)
        assert await state2.user_count() == 1
        # ...and load_state's post-recovery checkpoint made the snapshot
        # cover it, so a third boot replays nothing
        assert json.load(open(cfg.state_file))["wal_seq"] == durability2.wal.seq

    run(main())


# --- replay validation ------------------------------------------------------


def test_replay_rejects_what_the_rpc_would(tmp_path):
    st = ServerState()
    good = make_statement()
    eb = Ristretto255.element_to_bytes
    y1, y2 = eb(good.y1).hex(), eb(good.y2).hex()

    ok = st.replay_journal_record({
        "seq": 1, "type": "register_user", "user_id": "alice",
        "y1": y1, "y2": y2, "registered_at": 5,
    })
    assert ok is None and "alice" in st._users
    # the same trust boundary as restore(): bad ids, identity elements,
    # duplicates, unregistered session users, insane expiries, junk
    cases = [
        ({"seq": 2, "type": "register_user", "user_id": "bad user!",
          "y1": y1, "y2": y2, "registered_at": 1}, "invalid characters"),
        ({"seq": 3, "type": "register_user", "user_id": "eve",
          "y1": "00" * 32, "y2": y2, "registered_at": 1}, "identity"),
        ({"seq": 4, "type": "register_user", "user_id": "alice",
          "y1": y1, "y2": y2, "registered_at": 1}, "already registered"),
        ({"seq": 5, "type": "create_session", "token": "t",
          "user_id": "nobody", "created_at": 10, "expires_at": 20},
         "unregistered"),
        ({"seq": 6, "type": "create_session", "token": "t",
          "user_id": "alice", "created_at": 10, "expires_at": 10 ** 9},
         "expiry"),
        ({"seq": 7, "type": "revoke_session", "token": "ghost"}, "not found"),
        ({"seq": 8, "type": "mint_money", "amount": 1}, "unknown record"),
        ({"seq": 9, "type": "register_user"}, "malformed"),
        ({"seq": 10, "type": "register_user", "user_id": "mallory",
          "y1": "zz", "y2": y2, "registered_at": 1}, "malformed"),
        # challenge lifecycle records go through the same boundary
        ({"seq": 11, "type": "create_challenge", "challenge_id": "aa" * 32,
          "user_id": "nobody", "created_at": 10, "expires_at": 20},
         "unregistered"),
        ({"seq": 12, "type": "create_challenge", "challenge_id": "aa" * 32,
          "user_id": "alice", "created_at": 10, "expires_at": 10 ** 9},
         "expiry"),
        ({"seq": 13, "type": "consume_challenge", "challenge_id": "bb" * 32},
         "not found"),
        ({"seq": 14, "type": "create_challenge", "challenge_id": "zz",
          "user_id": "alice", "created_at": 10, "expires_at": 20},
         "malformed"),
    ]
    for rec, needle in cases:
        msg = st.replay_journal_record(rec)
        assert msg is not None and needle in msg, (rec, msg)
    assert list(st._users) == ["alice"] and not st._sessions


def test_replayed_expiry_sweep_matches_original(tmp_path):
    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        await register(state, 0)
        await state.create_session("live", "u0")
        state._sessions["dead"] = SessionData(
            token="dead", user_id="u0", created_at=1, expires_at=2
        )
        state._user_sessions["u0"].append("dead")
        # the dead session is journaled (direct map injection bypasses the
        # journal) only via the sweep record; replay must drop exactly it
        await state.cleanup_expired_sessions()
        state2, mgr2 = make_manager(tmp_path)
        await mgr2.recover()
        assert await state2.validate_session("live") == "u0"
        assert "dead" not in state2._sessions

    run(main())


# --- satellite: session clock-skew guard ------------------------------------


def test_session_expiry_has_clock_skew_guard():
    now = int(time.time())
    # clock stepped backward after mint: expires_at is still in the
    # (new) future, but the session is over twice its TTL old
    skewed = SessionData(
        token="t", user_id="u",
        created_at=now - 2 * SESSION_EXPIRY_SECONDS,
        expires_at=now + 1000,
    )
    assert skewed.is_expired()
    fresh = SessionData(token="t", user_id="u")
    assert not fresh.is_expired()
    # the guard takes an explicit clock like ChallengeData's
    assert fresh.is_expired(now + SESSION_EXPIRY_SECONDS + 1)
    assert not fresh.is_expired(now + 10)


# --- config + drift guard ---------------------------------------------------


def test_durability_config_layering_and_validation(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # no stray .env/config pickup
    cfg = ServerConfig.from_env()
    assert cfg.durability.enabled is False
    assert cfg.durability.fsync == "always"

    (tmp_path / "server.toml").write_text(
        '[durability]\nenabled = true\nfsync = "interval"\n'
        "compact_bytes = 4096\n"
    )
    monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
    monkeypatch.setenv("SERVER_STATE_FILE", str(tmp_path / "s.json"))
    cfg = ServerConfig.from_env()
    assert cfg.durability.enabled is True
    assert cfg.durability.fsync == "interval"
    assert cfg.durability.compact_bytes == 4096
    cfg.validate()
    # env overrides TOML
    monkeypatch.setenv("SERVER_DURABILITY_FSYNC", "OFF")
    monkeypatch.setenv("SERVER_DURABILITY_FSYNC_INTERVAL_MS", "125")
    monkeypatch.setenv("SERVER_DURABILITY_WAL_PATH", "/tmp/x.wal")
    cfg = ServerConfig.from_env()
    assert cfg.durability.fsync == "off"
    assert cfg.durability.fsync_interval_ms == 125.0
    assert cfg.durability.wal_path == "/tmp/x.wal"

    bad = ServerConfig()
    bad.durability.enabled = True  # without a state_file
    with pytest.raises(ValueError, match="requires state_file"):
        bad.validate()
    bad = ServerConfig()
    bad.durability.fsync = "sometimes"
    with pytest.raises(ValueError, match="durability.fsync"):
        bad.validate()
    bad = ServerConfig()
    bad.durability.compact_bytes = -1
    with pytest.raises(ValueError, match="compact_bytes"):
        bad.validate()


def test_durability_config_keys_documented():
    """CI drift guard: every [durability] knob ships in the TOML example,
    the .env example, and the operations-doc knob inventory."""
    keys = [f.name for f in dataclasses.fields(DurabilitySettings)]
    assert keys  # the guard itself must not silently go vacuous

    toml_text = (ROOT / "config" / "server.toml.example").read_text()
    m = re.search(r"^\[durability\]$", toml_text, re.M)
    assert m, "[durability] section missing from config/server.toml.example"
    section = toml_text[m.end():].split("\n[", 1)[0]
    env_text = (ROOT / ".env.example").read_text()
    docs = (ROOT / "docs" / "operations.md").read_text()
    for key in keys:
        assert re.search(rf"^{key}\s*=", section, re.M), (
            f"[durability] key {key!r} missing from config/server.toml.example"
        )
        assert f"SERVER_DURABILITY_{key.upper()}" in env_text, (
            f"SERVER_DURABILITY_{key.upper()} missing from .env.example"
        )
        assert f"`durability.{key}`" in docs, (
            f"`durability.{key}` missing from the docs/operations.md "
            "knob inventory"
        )


def test_persist_repl_command(tmp_path):
    from cpzk_tpu.server.__main__ import handle_command

    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        out, quit_ = await handle_command("/persist", state, None, None)
        assert "durability disabled" in out and not quit_
        await register(state, 0)
        await mgr.checkpoint()
        out, quit_ = await handle_command("/persist", state, None, mgr)
        assert not quit_
        assert f"seq={mgr.wal.seq}" in out
        assert f"covered_seq={mgr.covered_seq}" in out
        assert "fsync=always" in out and "last_fsync_age=" in out
        assert "snapshot_age=" in out and "n/a" not in out
        assert metrics.read("state.snapshot.age_seconds", "g") >= 0.0

    run(main())


def test_grpc_crash_recovery_without_any_snapshot(tmp_path):
    """End-to-end over the wire: register + login on a live gRPC server,
    hard-crash (no snapshot, no graceful close), reboot from the WAL
    alone, and log in WITHOUT re-registering — the acknowledged-RPC
    durability story the snapshot-only design could not tell."""
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.client.__main__ import do_login, do_register
    from cpzk_tpu.server import RateLimiter
    from cpzk_tpu.server.service import serve

    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        server, port = await serve(state, RateLimiter(1000, 1000), port=0)
        async with AuthClient(f"127.0.0.1:{port}") as c:
            assert "Registered" in await do_register(c, "carol", "pw-carol")
            assert "Login OK" in await do_login(c, "carol", "pw-carol")
        await server.stop(None)
        assert not os.path.exists(mgr.state_file)  # truly no snapshot

        state2, mgr2 = make_manager(tmp_path)
        report = await mgr2.recover()
        assert report.replayed >= 2  # the registration + the session mint
        assert await state2.session_count() == 1  # the login session too
        server2, port2 = await serve(state2, RateLimiter(1000, 1000), port=0)
        async with AuthClient(f"127.0.0.1:{port2}") as c:
            assert "Login OK" in await do_login(c, "carol", "pw-carol")
            assert "Login OK" not in await do_login(c, "carol", "wrong")
        await server2.stop(None)

    run(main())


def test_crash_mid_login_recovers_inflight_challenge(tmp_path):
    """Challenge lifecycle journaling (ISSUE 8 satellite): a challenge
    issued before a crash completes its login after the reboot — even
    when a snapshot landed in between (challenge records bypass the
    covered-seq replay cut, because snapshots deliberately exclude
    challenges) — and stays consume-once across a second reboot."""
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.client.__main__ import do_register
    from cpzk_tpu.client.kdf import password_to_scalar
    from cpzk_tpu.core.transcript import Transcript
    from cpzk_tpu.server import RateLimiter
    from cpzk_tpu.server.service import serve

    async def main():
        state, mgr = make_manager(tmp_path)
        await mgr.recover()
        server, port = await serve(state, RateLimiter(1000, 1000), port=0)
        async with AuthClient(f"127.0.0.1:{port}") as c:
            assert "Registered" in await do_register(c, "carol", "pw-carol")
            ch = await c.create_challenge("carol")
            cid = bytes(ch.challenge_id)
        # a cleanup-sweep snapshot lands between challenge creation and
        # the crash: users/sessions replay only past its covered seq, but
        # the in-flight challenge must still come back from the log
        assert await mgr.checkpoint() is True
        await server.stop(None)
        records = read_frames(mgr.wal_path)[0]
        assert any(r["type"] == "create_challenge" for r in records)

        # crash-reboot: the same challenge completes the login
        state2, mgr2 = make_manager(tmp_path)
        await mgr2.recover()
        assert await state2.challenge_count() == 1
        server2, port2 = await serve(state2, RateLimiter(1000, 1000), port=0)
        async with AuthClient(f"127.0.0.1:{port2}") as c:
            prover = Prover(params, Witness(password_to_scalar("pw-carol", "carol")))
            t = Transcript()
            t.append_context(cid)
            proof = prover.prove_with_transcript(rng, t)
            resp = await c.verify_proof("carol", cid, proof.to_bytes())
            assert resp.success and resp.session_token
        await server2.stop(None)

        # the consume was journaled too: a third boot does NOT resurrect
        # the spent challenge (consume-once survives the crash)
        state3, mgr3 = make_manager(tmp_path)
        await mgr3.recover()
        assert await state3.challenge_count() == 0
        assert await state3.session_count() == 1  # the minted session did
        mgr3.wal.close()
        mgr2.wal.close()
        mgr.wal.close()

    run(main())


# --- the real thing: SIGKILL a subprocess mid-traffic -----------------------


_KILL_CHILD = textwrap.dedent("""
    import asyncio, sys
    sys.path.insert(0, {root!r})

    from cpzk_tpu import Parameters, Prover, SecureRng, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.durability import DurabilityManager
    from cpzk_tpu.server.config import DurabilitySettings
    from cpzk_tpu.server.state import ServerState, UserData

    async def main():
        state = ServerState()
        mgr = DurabilityManager(
            state, DurabilitySettings(enabled=True, fsync="always"),
            {state_file!r},
        )
        await mgr.recover()
        rng, params = SecureRng(), Parameters.new()
        i = 0
        while True:
            stmt = Prover(params, Witness(Ristretto255.random_scalar(rng))).statement
            await state.register_user(UserData(f"user-{{i:04d}}", stmt, 1))
            # the register returned: the write is acknowledged (fsynced)
            print(f"ACK user-{{i:04d}}", flush=True)
            i += 1

    asyncio.run(main())
""")


@pytest.mark.slow
def test_sigkill_mid_traffic_recovers_every_acknowledged_write(tmp_path):
    """Register users in a real subprocess with fsync=always, SIGKILL it
    mid-traffic, reboot in-parent: every acknowledged write survived and
    no torn record applied."""
    state_file = str(tmp_path / "state.json")
    script = _KILL_CHILD.format(root=str(ROOT), state_file=state_file)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", script],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        cwd=str(ROOT),
    )
    acked = []
    try:
        deadline = time.monotonic() + 120
        while len(acked) < 8 and time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("ACK "):
                acked.append(line.split()[1])
        # kill without any grace, mid-traffic (likely mid-append)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:  # pragma: no cover - cleanup on failure
            proc.kill()
            proc.wait()
    assert len(acked) >= 8, (acked, proc.stderr.read())

    async def reboot():
        state = ServerState()
        mgr = DurabilityManager(
            state, DurabilitySettings(enabled=True), state_file
        )
        report = await mgr.recover()
        for uid in acked:
            assert await state.get_user(uid) is not None, (
                f"acknowledged write {uid} lost after SIGKILL ({report})"
            )
        # no torn record applied: the reopened log is byte-exact frames
        records, valid, total = read_frames(mgr.wal_path)
        assert valid == total
        # all surviving users are well-formed (no garbage applied)
        for uid in state._users:
            assert re.fullmatch(r"user-\d{4}", uid)

    run(reboot())


# --- segmented WAL (ISSUE 14 tentpole d) -------------------------------------


class TestSegmentedWal:
    def test_rotation_recovery_compaction_roundtrip(self, tmp_path):
        """Appends rotate into sealed segments, a crash-reboot replays the
        whole segmented history, a covering checkpoint compacts it to
        nothing (unlink, not copy), and graceful close leaves a log the
        next boot replays nothing from."""

        async def main():
            state, mgr = make_manager(
                tmp_path, wal_segment_bytes=500, compact_bytes=0
            )
            await mgr.recover()
            stmts = {i: make_statement() for i in range(40)}
            for i in range(40):
                await register(state, i, stmts[i])
            assert mgr.wal.segment_count > 3
            from cpzk_tpu.durability.wal import wal_sealed_segments

            names = [
                os.path.basename(p)
                for p in wal_sealed_segments(mgr.wal_path)
            ]
            assert names == sorted(names)  # name order IS seq order

            # crash (no close, no snapshot): reboot replays across segments
            state2, mgr2 = make_manager(
                tmp_path, wal_segment_bytes=500, compact_bytes=0
            )
            report = await mgr2.recover()
            assert report.replayed == 40
            assert await state2.user_count() == 40
            for i in (0, 17, 39):
                u = await state2.get_user(f"u{i}")
                assert u is not None and u.statement == stmts[i]

            # covering checkpoint: everything compacts away by unlink
            await mgr2.checkpoint()
            assert mgr2.wal.size == 0 and mgr2.wal.segment_count == 0
            assert wal_sealed_segments(mgr2.wal_path) == []

            await register(state2, 40)
            await mgr2.close()
            state3, mgr3 = make_manager(tmp_path, wal_segment_bytes=500)
            report3 = await mgr3.recover()
            assert await state3.user_count() == 41
            assert report3.replayed == 0
            mgr3.wal.close()

        run(main())

    def test_segmented_compaction_never_copies(self, tmp_path, monkeypatch):
        """The cliff this mode removes: compaction must not copy the
        surviving tail under the fd lock.  Spy: the copy path's tempfile
        is never created while sealed segments are being unlinked."""

        async def main():
            import cpzk_tpu.durability.wal as wal_mod

            copies = []
            real_mkstemp = wal_mod.tempfile.mkstemp

            def spy_mkstemp(*args, **kwargs):
                if ".compact." in kwargs.get("prefix", ""):
                    copies.append(kwargs["prefix"])
                return real_mkstemp(*args, **kwargs)

            monkeypatch.setattr(wal_mod.tempfile, "mkstemp", spy_mkstemp)
            state, mgr = make_manager(
                tmp_path, wal_segment_bytes=400, compact_bytes=0
            )
            await mgr.recover()
            for i in range(30):
                await register(state, i)
            segments_before = mgr.wal.segment_count
            assert segments_before > 2
            await mgr.checkpoint()
            assert mgr.wal.segment_count < segments_before
            assert copies == [], "segmented compaction copied the tail"
            mgr.wal.close()

        run(main())

    @pytest.mark.parametrize("point", ["pre_seal", "pre_unlink"])
    def test_segment_crash_points_recover_exactly(self, tmp_path, point):
        """FaultPlan matrix extension: dying at the seal rename or
        between compaction unlinks loses nothing — recovery replays the
        identical acknowledged prefix either way."""

        async def main():
            plan = FaultPlan().crash_on(point, occurrence=0)
            state, mgr = make_manager(
                tmp_path, plan=plan, wal_segment_bytes=400, compact_bytes=0
            )
            await mgr.recover()
            crashed = False
            for i in range(30):
                try:
                    await register(state, i)
                except CrashPoint:
                    crashed = True
                    break
            acked = 0
            for i in range(30):
                if (await state.get_user(f"u{i}")) is not None:
                    acked += 1
            if point == "pre_unlink":
                assert not crashed
                with pytest.raises(CrashPoint):
                    await mgr.checkpoint()  # dies between unlinks
            else:
                assert crashed  # the seal happens on the append's sync

            # reboot: exactly the acknowledged registrations, regardless
            # of which file the crash left half-rotated/half-compacted
            state2, mgr2 = make_manager(
                tmp_path, wal_segment_bytes=400, compact_bytes=0
            )
            await mgr2.recover()
            assert await state2.user_count() == acked
            for i in range(acked):
                assert await state2.get_user(f"u{i}") is not None
            # and the log keeps working: append + clean reboot sees it
            await register(state2, 90)
            await mgr2.checkpoint()
            state3, mgr3 = make_manager(
                tmp_path, wal_segment_bytes=400, compact_bytes=0
            )
            await mgr3.recover()
            assert await state3.get_user("u90") is not None
            mgr3.wal.close()
            mgr2.wal.close()

        run(main())

    def test_corrupt_sealed_segment_quarantines_suffix(self, tmp_path):
        """Sealed segments are fsynced before their rename, so interior
        corruption is a disk fault: recovery keeps the clean prefix and
        quarantines the corrupt file plus everything after the gap."""

        async def main():
            state, mgr = make_manager(
                tmp_path, wal_segment_bytes=400, compact_bytes=10**9
            )
            await mgr.recover()
            for i in range(30):
                await register(state, i)
            mgr.wal.close()
            from cpzk_tpu.durability.wal import wal_sealed_segments

            segs = wal_sealed_segments(mgr.wal_path)
            assert len(segs) >= 3
            with open(segs[1], "r+b") as f:  # clobber the SECOND segment
                f.write(b"\xff" * 32)

            state2, mgr2 = make_manager(tmp_path, wal_segment_bytes=400)
            report = await mgr2.recover()
            assert report.wal_quarantined is not None
            # the first segment's records survived; the poisoned suffix
            # (segment 2 onward) is quarantined, not applied
            count = await state2.user_count()
            assert 0 < count < 30
            remaining = wal_sealed_segments(mgr2.wal_path)
            assert all(".corrupt-" not in p for p in remaining)
            mgr2.wal.close()

        run(main())

    def test_wal_segment_bytes_config_layering(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "server.toml").write_text(
            "[durability]\nenabled = true\nwal_segment_bytes = 4096\n"
        )
        monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
        monkeypatch.setenv("SERVER_STATE_FILE", str(tmp_path / "s.json"))
        cfg = ServerConfig.from_env()
        assert cfg.durability.wal_segment_bytes == 4096
        cfg.validate()
        monkeypatch.setenv("SERVER_DURABILITY_WAL_SEGMENT_BYTES", "8192")
        cfg = ServerConfig.from_env()
        assert cfg.durability.wal_segment_bytes == 8192
        bad = ServerConfig()
        bad.durability.wal_segment_bytes = -1
        with pytest.raises(ValueError, match="wal_segment_bytes"):
            bad.validate()


# --- format-version stamps (ISSUE 18) ----------------------------------------


class TestFormatVersionStamps:
    """Every persisted record carries a ``fmt`` stamp; recovery refuses
    files NEWER than the build (naming both versions, never
    quarantining — the file is not corrupt, the binary is old) while
    unstamped pre-versioning files keep loading."""

    def test_wal_records_and_snapshot_are_stamped(self, tmp_path):
        from cpzk_tpu.durability import WAL_FORMAT_VERSION

        async def main():
            state, mgr = make_manager(tmp_path)
            await mgr.recover()
            await register(state, 0)
            await register(state, 1)
            mgr.wal.sync(True)
            with open(mgr.wal_path, "rb") as f:
                records, _ = iter_frames(f.read())
            assert records and all(
                r["fmt"] == WAL_FORMAT_VERSION for r in records
            )
            await mgr.checkpoint()
            doc = json.loads((tmp_path / "state.json").read_text())
            assert doc["version"] == ServerState.SNAPSHOT_VERSION
            mgr.wal.close()

        run(main())

    def test_recovery_refuses_newer_wal_record(self, tmp_path):
        from cpzk_tpu.durability import NewerFormatError, WAL_FORMAT_VERSION

        async def main():
            state, mgr = make_manager(tmp_path)
            await mgr.recover()
            await register(state, 0)
            mgr.wal.sync(True)
            seq = mgr.wal.seq
            mgr.wal.close()
            # a record from a NEWER build appended to the same log
            stmt = make_statement()
            eb = Ristretto255.element_to_bytes
            with open(mgr.wal_path, "ab") as f:
                f.write(encode_record({
                    "seq": seq + 1, "type": "register_user",
                    "fmt": WAL_FORMAT_VERSION + 1, "user_id": "future",
                    "y1": eb(stmt.y1).hex(), "y2": eb(stmt.y2).hex(),
                    "registered_at": 1,
                }))
            state2, mgr2 = make_manager(tmp_path)
            with pytest.raises(NewerFormatError) as exc:
                await mgr2.recover()
            msg = str(exc.value)
            assert f"format version {WAL_FORMAT_VERSION + 1}" in msg
            assert f"({WAL_FORMAT_VERSION})" in msg
            assert "state.json.wal" in msg  # names the refusing file
            # refusal, not quarantine: the log is left exactly in place
            assert os.path.exists(mgr.wal_path)
            assert not [
                p for p in os.listdir(tmp_path) if ".corrupt-" in p
            ]

        run(main())

    def test_unintelligible_wal_stamp_refuses(self, tmp_path):
        from cpzk_tpu.durability import NewerFormatError

        async def main():
            state, mgr = make_manager(tmp_path)
            await mgr.recover()
            mgr.wal.close()
            with open(mgr.wal_path, "ab") as f:
                f.write(encode_record({
                    "seq": 1, "type": "register_user", "fmt": "two",
                }))
            _state2, mgr2 = make_manager(tmp_path)
            with pytest.raises(NewerFormatError, match="unintelligible"):
                await mgr2.recover()

        run(main())

    def test_unstamped_wal_records_keep_loading(self, tmp_path):
        """Pre-versioning logs (no ``fmt`` key) replay exactly as before
        — absence IS version 1."""

        async def main():
            stmt = make_statement()
            eb = Ristretto255.element_to_bytes
            wal_path = str(tmp_path / "state.json.wal")
            with open(wal_path, "wb") as f:
                f.write(encode_record({
                    "seq": 1, "type": "register_user", "user_id": "old",
                    "y1": eb(stmt.y1).hex(), "y2": eb(stmt.y2).hex(),
                    "registered_at": 1,
                }))
            state, mgr = make_manager(tmp_path)
            report = await mgr.recover()
            assert report.replayed == 1
            assert (await state.get_user("old")) is not None
            mgr.wal.close()

        run(main())

    def test_snapshot_newer_version_refuses_not_quarantines(self, tmp_path):
        from cpzk_tpu.durability import NewerFormatError

        async def main():
            state, mgr = make_manager(tmp_path)
            await mgr.recover()
            await register(state, 0)
            await mgr.checkpoint()
            mgr.wal.close()
            snap = tmp_path / "state.json"
            doc = json.loads(snap.read_text())
            doc["version"] = ServerState.SNAPSHOT_VERSION + 1
            snap.write_text(json.dumps(doc))
            _state2, mgr2 = make_manager(tmp_path)
            with pytest.raises(NewerFormatError) as exc:
                await mgr2.recover()
            msg = str(exc.value)
            assert f"version {ServerState.SNAPSHOT_VERSION + 1}" in msg
            assert "newer than this build" in msg
            assert "state.json" in msg
            # the snapshot stays where it is — no quarantine sibling
            assert snap.exists()
            assert not [
                p for p in os.listdir(tmp_path) if ".corrupt-" in p
            ]
            # junk stamps refuse too (never half-trusted)
            doc["version"] = "zzz"
            snap.write_text(json.dumps(doc))
            _state3, mgr3 = make_manager(tmp_path)
            with pytest.raises(NewerFormatError, match="zzz"):
                await mgr3.recover()

        run(main())

    def test_unstamped_snapshot_keeps_loading(self, tmp_path):
        async def main():
            state, mgr = make_manager(tmp_path)
            await mgr.recover()
            await register(state, 0)
            await mgr.checkpoint()
            mgr.wal.close()
            snap = tmp_path / "state.json"
            doc = json.loads(snap.read_text())
            del doc["version"]
            snap.write_text(json.dumps(doc))
            state2, mgr2 = make_manager(tmp_path)
            report = await mgr2.recover()
            assert report.snapshot_loaded
            assert (await state2.get_user("u0")) is not None
            mgr2.wal.close()

        run(main())

    def test_proof_log_stamped_and_refuses_newer(self, tmp_path):
        from cpzk_tpu.audit.log import ProofLogWriter
        from cpzk_tpu.durability import NewerFormatError, WAL_FORMAT_VERSION

        path = str(tmp_path / "proofs.log")
        w = ProofLogWriter(path)
        w.append_proofs([{"user_id": "a", "ok": True}])
        w.close()
        with open(path, "rb") as f:
            records, _ = iter_frames(f.read())
        assert records[0]["fmt"] == WAL_FORMAT_VERSION
        # reopening over a record from a newer build refuses at init
        with open(path, "ab") as f:
            f.write(encode_record({
                "seq": 2, "type": "proof",
                "fmt": WAL_FORMAT_VERSION + 1, "user_id": "b",
            }))
        with pytest.raises(NewerFormatError) as exc:
            ProofLogWriter(path)
        assert "proof log" in str(exc.value)
        assert f"format version {WAL_FORMAT_VERSION + 1}" in str(exc.value)
        # the unstamped/older prefix alone reopens fine
        w2 = ProofLogWriter(str(tmp_path / "other.log"))
        w2.append_proofs([{"user_id": "c", "ok": False}])
        w2.close()
        assert ProofLogWriter(str(tmp_path / "other.log")).seq == 1
