"""Ristretto255 group tests against RFC 9496 vectors + reference API parity
(mirrors the inline tests in reference src/primitives/ristretto.rs:224-329)."""

import pytest

from cpzk_tpu.core import edwards
from cpzk_tpu.core.ristretto import Element, Ristretto255, Scalar
from cpzk_tpu.core.rng import SecureRng
from cpzk_tpu.errors import InvalidGroupElement, InvalidScalar

# RFC 9496 appendix A: first multiples of the ristretto255 generator.
SMALL_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
]


def test_small_multiples():
    acc = edwards.IDENTITY
    for expected in SMALL_MULTIPLES:
        assert edwards.ristretto_encode(acc).hex() == expected
        acc = edwards.pt_add(acc, edwards.BASEPOINT)


def test_one_way_map_vector():
    """RFC 9496 one-way map vector (dalek/libsodium 'espresso' vector) —
    guards the sign of SQRT_AD_MINUS_ONE, which the squaring-only constant
    test cannot see."""
    import hashlib

    digest = hashlib.sha512(
        b"Ristretto is traditionally a short shot of espresso coffee"
    ).digest()
    point = edwards.ristretto_from_uniform_bytes(digest)
    assert (
        edwards.ristretto_encode(point).hex()
        == "3066f82a1a747d45120d1740f14358531a8f04bbffe6a819f86dfe50f44a0a46"
    )


def test_decode_rejects_noncanonical():
    # s >= p
    bad = (edwards.P + 2).to_bytes(32, "little")
    assert edwards.ristretto_decode(bad) is None
    # negative (odd) s
    assert edwards.ristretto_decode((3).to_bytes(32, "little")) is None
    # all-ones
    assert edwards.ristretto_decode(b"\xff" * 32) is None
    # wrong length via API
    with pytest.raises(InvalidGroupElement):
        Ristretto255.element_from_bytes(b"\x00" * 31)


def test_generators_distinct_and_valid():
    g = Ristretto255.generator_g()
    h = Ristretto255.generator_h()
    assert g != h
    assert not Ristretto255.is_identity(g)
    assert not Ristretto255.is_identity(h)
    Ristretto255.validate_element(g)
    Ristretto255.validate_element(h)
    # deterministic
    assert Ristretto255.element_to_bytes(h) == Ristretto255.element_to_bytes(Ristretto255.generator_h())


def test_scalar_roundtrip_and_ops():
    rng = SecureRng()
    a = Ristretto255.random_scalar(rng)
    b = Ristretto255.random_scalar(rng)
    assert Ristretto255.scalar_sub(Ristretto255.scalar_add(a, b), b) == a
    assert Ristretto255.scalar_mul_scalar(a, b) == Ristretto255.scalar_mul_scalar(b, a)
    inv = Ristretto255.scalar_invert(a)
    assert Ristretto255.scalar_mul_scalar(a, inv) == Scalar(1)
    assert Ristretto255.scalar_invert(Scalar(0)) is None
    data = Ristretto255.scalar_to_bytes(a)
    assert Ristretto255.scalar_from_bytes(data) == a
    with pytest.raises(InvalidScalar):
        Ristretto255.scalar_from_bytes(b"\xff" * 32)


def test_element_roundtrip_and_group_law():
    rng = SecureRng()
    g = Ristretto255.generator_g()
    a = Ristretto255.random_scalar(rng)
    b = Ristretto255.random_scalar(rng)
    ga = Ristretto255.scalar_mul(g, a)
    gb = Ristretto255.scalar_mul(g, b)
    # serialization roundtrip
    data = Ristretto255.element_to_bytes(ga)
    assert Ristretto255.element_from_bytes(data) == ga
    # homomorphism: g^a * g^b == g^(a+b)
    lhs = Ristretto255.element_mul(ga, gb)
    rhs = Ristretto255.scalar_mul(g, Ristretto255.scalar_add(a, b))
    assert lhs == rhs
    Ristretto255.validate_element(ga)


def test_identity():
    ident = Ristretto255.identity()
    assert Ristretto255.is_identity(ident)
    assert not Ristretto255.is_identity(Ristretto255.generator_g())
    assert Ristretto255.element_to_bytes(ident) == b"\x00" * 32
    Ristretto255.validate_element(ident)


def test_torsion_coset_equality():
    # The 2-torsion point (0, -1) is in the identity coset.
    t = (0, edwards.P - 1, 1, 0)
    assert Element(t) == Ristretto255.identity()
    assert edwards.ristretto_encode(t) == b"\x00" * 32
