"""Fuzz target: the admission subsystem's structural invariants.

Arbitrary bytes drive a schedule of admission decisions — hostile client
keys, garbage RPC names, adversarial clock jumps — against a controller
with byte-derived settings.  Invariants:

- the keyed-bucket table NEVER exceeds its LRU bound, no matter how many
  distinct keys the input mints (the keyspace is not a memory-DoS
  primitive);
- ``classify`` is total: any input maps to a known tier, never raises;
- ``AdmissionController.admit`` never raises on arbitrary rpc/key input;
  a rejection always carries a pushback inside the configured
  ``[retry_after_min_ms, retry_after_max_ms]`` bounds and a known reason;
- the admission level stays inside ``[MIN_LEVEL, N_TIERS]`` under any
  signal sequence, and the priority ordering is structural: whenever a
  tier is admitted, every higher-priority (lower-numbered) tier is too.

Run: python fuzz/fuzz_admission.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

from common import run_fuzzer

from cpzk_tpu.admission import (
    MIN_LEVEL,
    N_TIERS,
    AdmissionController,
    classify,
)
from cpzk_tpu.server.config import AdmissionSettings


def _seeds() -> list[bytes]:
    return [
        b"\x08\x04" + b"client-a" * 4 + b"\xff" * 8,
        bytes(range(64)),
        b"VerifyProofRegisterCreateChallenge" + b"\x00\x01\x02\x03",
    ]


_RPCS = ["VerifyProof", "CreateChallenge", "Register", "RegisterBatch",
         "VerifyProofBatch", "", "Bogus", None]


def one_input(data: bytes) -> None:
    if len(data) < 4:
        data = data + b"\x00" * 4
    max_clients = 1 + data[0] % 32
    settings = AdmissionSettings(
        per_client_rpm=(data[1] % 4) * 30,  # includes 0 = disabled
        per_client_burst=1 + data[2] % 8,
        max_clients=max_clients,
        adjust_interval_ms=1.0 + data[3],
        increase_step=0.5,
        decrease_factor=0.5,
    )
    now = [0.0]
    sig = [0.0, 0.0]
    controller = AdmissionController(
        settings, clock=lambda: now[0], signals=lambda: (sig[0], sig[1])
    )
    lo = settings.retry_after_min_ms / 1000.0
    hi = settings.retry_after_max_ms / 1000.0

    for i in range(0, len(data) - 2, 3):
        op, a, b = data[i], data[i + 1], data[i + 2]
        now[0] += (op % 16) * 0.05
        sig[0] = a / 255.0  # utilization sweep: healthy <-> overloaded
        sig[1] = (b / 255.0) * 0.2
        rpc = _RPCS[a % len(_RPCS)]
        if op % 5 == 0:
            rpc = data[i: i + 8].decode("latin-1")  # arbitrary rpc name
        key = data[b % max(1, len(data)):][:16].decode("latin-1") or "k"

        tier = classify(rpc)
        assert tier in (0, 1, 2)

        rejection = controller.admit(rpc, key)
        assert len(controller.buckets) <= max_clients
        assert MIN_LEVEL <= controller.level <= float(N_TIERS)
        if rejection is not None:
            assert rejection.reason in ("per_client", "priority")
            assert lo <= rejection.retry_after_s <= hi
            assert isinstance(rejection.message, str) and rejection.message
            if rejection.reason == "priority":
                # ordering is structural: only tiers at/above the level
                # are shed, and the MIN_LEVEL floor exempts tier 0
                # (VerifyProof) from priority shedding entirely
                assert rejection.tier >= controller.level
                assert rejection.tier > 0


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
