"""Fuzz target: native wire parse ≡ protobuf parse on arbitrary bytes.

The native request scanner (``native/wire.cpp`` via ``server/wire.py``)
sits on the gRPC deserializer seam — a trust boundary fed raw socket
bytes.  Its safety contract is NOT "parses everything correctly"; it is
"either produce exactly what the Python protobuf runtime would, or punt
to it".  This target holds that differentially, per message kind:

- the parser (and the view materialization behind it) never crashes on
  arbitrary bytes — it returns a view or ``None`` (punt);
- whenever it ACCEPTS, the protobuf runtime must also accept, and every
  decoded field is byte/value-equal to the protobuf message's
  (``user_id(s)``, ``challenge_ids``, ``proofs``, packed/unpacked
  ``ids``, ``mint_sessions`` last-wins);
- the packed-proof staging buffer, when claimed, is exactly the
  concatenation of the proofs at canonical size;
- rejection parity is structural: on punt the deserializer IS
  ``FromString``, so accept/reject can never diverge — asserted here by
  construction (a punt with a protobuf-accepted message is fine, a
  native accept with a protobuf rejection is a violation).

Run: python fuzz/fuzz_wire_parse.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

import random

from common import run_fuzzer

from cpzk_tpu.server import wire as wire_mod
from cpzk_tpu.server.proto import load_pb2

pb2 = load_pb2()

_KINDS = (
    (pb2.ChallengeRequest, wire_mod._parse_challenge),
    (pb2.BatchVerificationRequest, wire_mod._parse_batch_verify),
    (pb2.StreamVerifyRequest, wire_mod._parse_stream_chunk),
)

_PROOF = 109


def _seeds() -> list[bytes]:
    rng = random.Random(7)
    seeds = [
        pb2.ChallengeRequest(user_id="alice").SerializeToString(),
        pb2.ChallengeRequest(user_id="héllo-ü\U0001F600").SerializeToString(),
        pb2.ChallengeRequest().SerializeToString(),
        pb2.BatchVerificationRequest(
            user_ids=["a", "b", "c"],
            challenge_ids=[b"\x01" * 33, b"", b"\x02" * 64],
            proofs=[bytes(_PROOF), b"x", bytes(_PROOF)],
        ).SerializeToString(),
        pb2.StreamVerifyRequest(
            ids=[0, 1, 2**64 - 1],
            user_ids=["u1", "u2", "u3"],
            challenge_ids=[b"c" * 33] * 3,
            proofs=[bytes([rng.randrange(256)] * _PROOF) for _ in range(3)],
            mint_sessions=True,
        ).SerializeToString(),
        # unpacked varint ids (legal proto3 encoding the client never emits)
        b"\x08\x2a\x08\x00" + pb2.StreamVerifyRequest(
            user_ids=["x"], challenge_ids=[b"y"], proofs=[b"z"]
        ).SerializeToString(),
        b"",
        b"\x0a\x00",
    ]
    return seeds


def _ref_parse(cls, data: bytes):
    try:
        return cls.FromString(data)
    except Exception:
        return None


def _check_kind(cls, parser, data: bytes) -> None:
    try:
        view = parser(data)
    except Exception as exc:  # the parser must NEVER raise
        raise AssertionError(
            f"native parser raised on arbitrary bytes: {exc!r}"
        ) from exc
    if view is None:
        return  # punt: the deserializer is FromString — parity structural
    ref = _ref_parse(cls, data)
    assert ref is not None, (
        "native parser accepted bytes the protobuf runtime rejects"
    )
    if cls is pb2.ChallengeRequest:
        assert view.user_id == ref.user_id
        return
    assert view.user_ids == list(ref.user_ids)
    assert view.challenge_ids == list(ref.challenge_ids)
    assert view.proofs == list(ref.proofs)
    if view.proofs_packed is not None:
        assert all(len(p) == _PROOF for p in ref.proofs)
        assert view.proofs_packed == b"".join(ref.proofs)
        assert view.packed_proofs(len(ref.proofs)) == view.proofs_packed
    if cls is pb2.StreamVerifyRequest:
        assert view.ids == list(ref.ids)
        assert view.mint_sessions == ref.mint_sessions


def one_input(data: bytes) -> None:
    for cls, parser in _KINDS:
        _check_kind(cls, parser, data)


if __name__ == "__main__":
    if not wire_mod.native_available():
        print("native core unavailable; nothing to fuzz")
        raise SystemExit(0)
    run_fuzzer(one_input, _seeds())
