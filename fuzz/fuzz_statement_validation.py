"""Fuzz target: statement/element decoding + validation
(reference analog ``fuzz/fuzz_targets/fuzz_statement_validation.rs``;
decoder under test mirrors ``src/primitives/ristretto.rs:94-138`` and
``gadgets.rs:217-238``).

Invariants:
- ``element_from_bytes`` / ``scalar_from_bytes`` either succeed or raise
  ``cpzk_tpu.Error`` — never another exception;
- a decoded element re-encodes to the same 32 bytes (canonical encoding);
- ``Statement.validate`` never crashes on decodable input pairs.

Run: python fuzz/fuzz_statement_validation.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

from common import run_fuzzer

from cpzk_tpu import Error, Statement
from cpzk_tpu.core.ristretto import Ristretto255


def _seeds() -> list[bytes]:
    g = Ristretto255.generator_g()
    h = Ristretto255.generator_h()
    gb = Ristretto255.element_to_bytes(g)
    hb = Ristretto255.element_to_bytes(h)
    return [gb + hb, gb + gb, bytes(32) + hb, gb, hb + bytes(64)]


def one_input(data: bytes) -> None:
    half = len(data) // 2
    y1b, y2b = data[:half], data[half:]
    try:
        y1 = Ristretto255.element_from_bytes(y1b)
    except Error:
        return
    # canonical re-encode invariant on the accepted element
    assert Ristretto255.element_to_bytes(y1) == bytes(y1b), "non-canonical element"
    try:
        y2 = Ristretto255.element_from_bytes(y2b)
    except Error:
        return
    try:
        Statement(y1, y2).validate()
    except Error:
        return

    # scalar path on the same raw bytes
    try:
        s = Ristretto255.scalar_from_bytes(y1b)
    except Error:
        return
    assert Ristretto255.scalar_to_bytes(s) == bytes(y1b), "non-canonical scalar"


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
