"""Fuzz target: WAL frame parsing + boot-time record replay.

Arbitrary bytes presented as a write-ahead log must yield clean
truncate-at-tail recovery — never an exception, never garbage state
(the durability subsystem's trust-boundary contract).

Invariants:
- ``iter_frames`` never raises; the valid prefix is a byte offset within
  the input, every parsed record is a dict with an int ``seq`` (strictly
  increasing) and str ``type``;
- parsing is **prefix-stable**: re-parsing the valid prefix alone yields
  the same records and consumes it fully (what recovery's truncation
  relies on — truncating at the boundary loses nothing that parsed);
- ``ServerState.replay_journal_record`` never raises on any parsed
  record — malformed fields come back as skip reasons, and whatever does
  apply passes the registration-time validators (user-id rules, no
  identity statement elements, session expiry sanity).

Run: python fuzz/fuzz_wal_replay.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

from common import run_fuzzer

from cpzk_tpu.durability.wal import encode_record, iter_frames
from cpzk_tpu.server.state import ServerState, user_id_error


def _seeds() -> list[bytes]:
    from cpzk_tpu import Parameters, Prover, SecureRng, Witness
    from cpzk_tpu.core.ristretto import Ristretto255

    rng, params = SecureRng(), Parameters.new()
    eb = Ristretto255.element_to_bytes
    frames = []
    for i in range(3):
        st = Prover(params, Witness(Ristretto255.random_scalar(rng))).statement
        frames.append(encode_record({
            "seq": 2 * i + 1, "type": "register_user", "user_id": f"user-{i}",
            "y1": eb(st.y1).hex(), "y2": eb(st.y2).hex(), "registered_at": 1,
        }))
        frames.append(encode_record({
            "seq": 2 * i + 2, "type": "create_session", "token": f"tok-{i}",
            "user_id": f"user-{i}", "created_at": 10 ** 10,
            "expires_at": 10 ** 10 + 60,
        }))
    frames.append(encode_record({"seq": 7, "type": "revoke_session",
                                 "token": "tok-0"}))
    frames.append(encode_record({"seq": 8, "type": "expire_sessions",
                                 "now": 10 ** 10}))
    full = b"".join(frames)
    return [full, frames[0], full[: len(full) // 2]]


def one_input(data: bytes) -> None:
    records, valid = iter_frames(data)
    assert 0 <= valid <= len(data)
    prev = None
    for rec in records:
        assert isinstance(rec, dict)
        assert isinstance(rec["seq"], int) and isinstance(rec["type"], str)
        assert prev is None or rec["seq"] > prev
        prev = rec["seq"]

    # prefix stability: truncating at the boundary loses nothing
    again, valid2 = iter_frames(data[:valid])
    assert valid2 == valid and again == records

    # replay must never raise; applied records passed the validators
    state = ServerState()
    for rec in records:
        msg = state.replay_journal_record(rec)
        assert msg is None or isinstance(msg, str)
    for uid in state._users:
        assert user_id_error(uid) is None, f"validator bypass: {uid!r}"
    for token, sess in state._sessions.items():
        assert sess.user_id in state._users, "session for unregistered user"
        assert 0 < sess.expires_at - sess.created_at <= 3600


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
