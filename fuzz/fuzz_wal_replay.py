"""Fuzz target: WAL frame parsing, boot-time replay, segmented delivery.

Arbitrary bytes presented as a write-ahead log must yield clean
truncate-at-tail recovery — never an exception, never garbage state
(the durability subsystem's trust-boundary contract).  The same bytes
re-packaged as replication segments and delivered adversarially
(duplicated, reordered, truncated, cross-epoch) must leave the standby
applier in a prefix-stable state (ISSUE 8 satellite).

Invariants:
- ``iter_frames`` never raises; the valid prefix is a byte offset within
  the input, every parsed record is a dict with an int ``seq`` (strictly
  increasing) and str ``type``;
- parsing is **prefix-stable**: re-parsing the valid prefix alone yields
  the same records and consumes it fully (what recovery's truncation
  relies on — truncating at the boundary loses nothing that parsed);
- ``ServerState.replay_journal_record`` never raises on any parsed
  record — malformed fields come back as skip reasons, and whatever does
  apply passes the registration-time validators (user-id rules, no
  identity statement elements, session expiry sanity);
- ``SegmentApplier`` never raises on any delivery schedule; its
  ``applied_seq`` is monotonic; a torn or tampered segment changes
  nothing; duplicates never double-apply; a lower-epoch segment after a
  higher one is always fenced.

Run: python fuzz/fuzz_wal_replay.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

import dataclasses
import random
import zlib

from common import run_fuzzer

from cpzk_tpu.durability.wal import encode_record, iter_frames
from cpzk_tpu.replication import SegmentApplier, split_records
from cpzk_tpu.server.state import ServerState, user_id_error


def _seeds() -> list[bytes]:
    from cpzk_tpu import Parameters, Prover, SecureRng, Witness
    from cpzk_tpu.core.ristretto import Ristretto255

    rng, params = SecureRng(), Parameters.new()
    eb = Ristretto255.element_to_bytes
    frames = []
    for i in range(3):
        st = Prover(params, Witness(Ristretto255.random_scalar(rng))).statement
        frames.append(encode_record({
            "seq": 2 * i + 1, "type": "register_user", "user_id": f"user-{i}",
            "y1": eb(st.y1).hex(), "y2": eb(st.y2).hex(), "registered_at": 1,
        }))
        frames.append(encode_record({
            "seq": 2 * i + 2, "type": "create_session", "token": f"tok-{i}",
            "user_id": f"user-{i}", "created_at": 10 ** 10,
            "expires_at": 10 ** 10 + 60,
        }))
    frames.append(encode_record({"seq": 7, "type": "revoke_session",
                                 "token": "tok-0"}))
    frames.append(encode_record({"seq": 8, "type": "expire_sessions",
                                 "now": 10 ** 10}))
    full = b"".join(frames)
    return [full, frames[0], full[: len(full) // 2]]


def _segment_delivery(records: list[dict], data: bytes) -> None:
    """Re-package the parsed records as segments and deliver them through
    an adversarial schedule derived deterministically from the input."""
    if not records:
        return
    rnd = random.Random(zlib.crc32(data))
    segs = split_records(
        records, epoch=2, first_index=0,
        segment_bytes=rnd.choice((1, 64, 300, 1 << 16)),
    )
    schedule = list(segs)
    # duplicates, reordering, truncation/tamper, cross-epoch deliveries
    schedule += rnd.sample(segs, k=min(2, len(segs)))
    rnd.shuffle(schedule)
    mutated = []
    for seg in schedule:
        roll = rnd.random()
        if roll < 0.25 and len(seg.frames) > 1:
            cut = rnd.randrange(1, len(seg.frames))
            mutated.append(dataclasses.replace(seg, frames=seg.frames[:cut]))
        elif roll < 0.4:
            mutated.append(dataclasses.replace(seg, epoch=rnd.choice((1, 3))))
        elif roll < 0.5:
            mutated.append(dataclasses.replace(seg, crc=seg.crc ^ 0x1))
        else:
            mutated.append(seg)

    state = ServerState()
    applier = SegmentApplier(state, epoch=2)
    prev_applied = 0
    for seg in mutated:
        accepted, message = applier.apply(seg)  # must never raise
        assert isinstance(accepted, bool) and isinstance(message, str)
        assert applier.applied_seq >= prev_applied  # monotonic, never back
        prev_applied = applier.applied_seq
        if seg.epoch < applier.epoch:
            assert not accepted  # fencing is unconditional
    for uid in state._users:
        assert user_id_error(uid) is None

    # prefix-stability: an in-order delivery applies the contiguous
    # prefix; re-delivering the same segments is pure no-op — duplicates
    # for the applied prefix, the same gap rejection for the rest
    fresh = SegmentApplier(
        ServerState(), epoch=2, applied_seq=records[0]["seq"] - 1
    )
    for seg in segs:
        fresh.apply(seg)
    applied_now = fresh.applied_seq
    for seg in segs:
        accepted, message = fresh.apply(seg)
        if seg.last_seq <= applied_now:
            assert accepted and "duplicate" in message
        else:
            assert not accepted and "gap" in message
    assert fresh.applied_seq == applied_now


def one_input(data: bytes) -> None:
    records, valid = iter_frames(data)
    assert 0 <= valid <= len(data)
    prev = None
    for rec in records:
        assert isinstance(rec, dict)
        assert isinstance(rec["seq"], int) and isinstance(rec["type"], str)
        assert prev is None or rec["seq"] > prev
        prev = rec["seq"]

    # prefix stability: truncating at the boundary loses nothing
    again, valid2 = iter_frames(data[:valid])
    assert valid2 == valid and again == records

    # replay must never raise; applied records passed the validators
    state = ServerState()
    for rec in records:
        msg = state.replay_journal_record(rec)
        assert msg is None or isinstance(msg, str)
    for uid in state._users:
        assert user_id_error(uid) is None, f"validator bypass: {uid!r}"
    for token, sess in state._sessions.items():
        assert sess.user_id in state._users, "session for unregistered user"
        assert 0 < sess.expires_at - sess.created_at <= 3600

    # the same records as an adversarially-delivered segment stream
    _segment_delivery(records, data)


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
