"""Fuzz target: ``Proof.from_bytes`` — the adversarial input surface
(reference analog ``fuzz/fuzz_targets/fuzz_proof_deserialization.rs``;
parser under test mirrors ``src/primitives/gadgets.rs:364-489``).

Invariants:
- any input either parses or raises ``cpzk_tpu.Error`` — never another
  exception type, never a crash;
- a successful parse round-trips: ``to_bytes()`` reproduces the exact
  input (the wire format is canonical);
- a parsed proof never contains identity commitments or a zero response
  (the parser's own rejection rules).

Run: python fuzz/fuzz_proof_deserialization.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

from common import run_fuzzer

from cpzk_tpu import Error, Parameters, Proof, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.core.ristretto import Ristretto255


def _seeds() -> list[bytes]:
    rng = SecureRng()
    params = Parameters.new()
    out = []
    for _ in range(4):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        out.append(prover.prove_with_transcript(rng, Transcript()).to_bytes())
    return out


def one_input(data: bytes) -> None:
    try:
        proof = Proof.from_bytes(data)
        verdict = "OK"
    except Error as e:
        proof = None
        verdict = f"{type(e).__name__}: {e}"

    # three-way parse differential: the batched native pass and the
    # deferred-parse pipeline (frame check now, point decodes settled by
    # the dispatcher's screening) must agree with the eager parser on
    # accept/reject AND on the exact error
    b_eager, = Proof.from_bytes_batch([data])
    b_defer, = Proof.from_bytes_batch([data], defer_point_validation=True)
    if isinstance(b_eager, Proof):
        assert verdict == "OK", f"batch accepted what eager rejected: {verdict}"
    else:
        assert verdict == f"{type(b_eager).__name__}: {b_eager}", (
            verdict, f"{type(b_eager).__name__}: {b_eager}")
    if isinstance(b_defer, Proof):
        if b_defer.deferred:  # settle the postponed decodes like verify does
            from cpzk_tpu.protocol.batch import BatchEntry, BatchVerifier
            from cpzk_tpu.protocol.gadgets import Parameters

            bv = BatchVerifier()
            bv.entries.append(BatchEntry(Parameters.new(), None, b_defer, None))
            errs = bv._screen_deferred()
            if verdict == "OK":
                assert not errs, f"screening rejected an eager-valid wire: {errs}"
            else:
                assert 0 in errs, f"deferred pipeline accepted: {verdict}"
        else:
            assert verdict == "OK"
    else:
        assert verdict == f"{type(b_defer).__name__}: {b_defer}", (
            verdict, f"{type(b_defer).__name__}: {b_defer}")

    if proof is None:
        return  # expected rejection path
    # canonical wire format: parse -> serialize must be the identity
    assert proof.to_bytes() == bytes(data), "non-canonical accept"
    assert not Ristretto255.is_identity(proof.commitment.r1), "identity r1 accepted"
    assert not Ristretto255.is_identity(proof.commitment.r2), "identity r2 accepted"
    assert proof.response.s.value != 0, "zero response accepted"


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
