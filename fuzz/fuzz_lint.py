"""Fuzz target: cpzk-lint never crashes on parseable source.

Invariant: for ANY byte blob, the analyzer either returns a report
(possibly containing PARSE-001 findings) or — never — raises.  Inputs
that happen to be valid Python exercise the taint pass, the waiver
parser, and every rule's visitor over adversarial ASTs; inputs that are
not valid Python must come back as a single PARSE-001 finding, not an
exception.  Findings are re-rendered and serialized so the reporting
path is covered too.

Run standalone: ``python fuzz_lint.py --seconds 15`` (see common.py).
"""

from __future__ import annotations

import json

from common import run_fuzzer

from cpzk_tpu.analysis import analyze_source

_SEED_SNIPPETS = [
    b"",
    b"x = 1\n",
    b"# cpzk-lint: disable=CT-001 -- seed reason\nx = 1 == 2\n",
    b"# cpzk-lint: disable=LOCK-001\n",
    b"def f(password):\n    return password == 'x'\n",
    b"""\
import asyncio, time
class ServerState:
    async def mutate(self):
        self._users['a'] = 1
        time.sleep(1)
        asyncio.create_task(self.mutate())
""",
    b"""\
import jax
@jax.jit
def f(x):
    import time
    return time.time()
""",
    b"""\
async def handler(self, request, context):
    await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "x")
""",
    b"f'{witness.secret().value}'\n",
    b"while witness.secret():\n    pass\n",
]


def _seeds() -> list[bytes]:
    return list(_SEED_SNIPPETS)


def one_input(data: bytes) -> None:
    try:
        source = data.decode()
    except UnicodeDecodeError:
        source = data.decode("utf-8", "replace")
    # rotate the virtual path so plane-scoped rules all get exercised
    plane = ("core", "protocol", "server", "client", "ops", "")[len(data) % 6]
    path = f"cpzk_tpu/{plane}/fuzzed.py" if plane else "fuzzed.py"
    report = analyze_source(source, path=path)
    # the reporting path must hold too: render + JSON round-trip
    for f in report.findings + report.waived:
        assert f.render()
    json.dumps(report.to_dict())


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
