"""Fuzz target: cpzk-lint never crashes on parseable source.

Invariant: for ANY byte blob, the analyzer either returns a report
(possibly containing PARSE-001 findings) or — never — raises.  Inputs
that happen to be valid Python exercise the taint pass, the waiver
parser, and every rule's visitor over adversarial ASTs; inputs that are
not valid Python must come back as a single PARSE-001 finding, not an
exception.  Findings are re-rendered and serialized so the reporting
path is covered too.

Run standalone: ``python fuzz_lint.py --seconds 15`` (see common.py).
"""

from __future__ import annotations

import json

from common import run_fuzzer

from cpzk_tpu.analysis import analyze_source

_SEED_SNIPPETS = [
    b"",
    b"x = 1\n",
    b"# cpzk-lint: disable=CT-001 -- seed reason\nx = 1 == 2\n",
    b"# cpzk-lint: disable=LOCK-001\n",
    b"def f(password):\n    return password == 'x'\n",
    b"""\
import asyncio, time
class ServerState:
    async def mutate(self):
        self._users['a'] = 1
        time.sleep(1)
        asyncio.create_task(self.mutate())
""",
    b"""\
import jax
@jax.jit
def f(x):
    import time
    return time.time()
""",
    b"""\
async def handler(self, request, context):
    await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "x")
""",
    b"f'{witness.secret().value}'\n",
    b"while witness.secret():\n    pass\n",
    # threaded/process-spawning shapes: the execution-context inference
    # (call graph, spawn-site seeding, propagation) and the context rules
    # (THREAD-001/PROC-001) must hold their invariants over mutations of
    # these too — self-referential spawns, nested defs, bound targets
    b"""\
import asyncio, threading
class Lane:
    def start(self):
        threading.Thread(target=self._loop).start()
    def _loop(self):
        self._post()
    def _post(self):
        def _resolve():
            self.fut.set_result(1)
        self.loop.call_soon_threadsafe(_resolve)
        self.fut.set_exception(ValueError())
""",
    b"""\
import multiprocessing, threading
def child(x):
    return x
class Sup:
    def spawn(self):
        lock = threading.Lock()
        ctx = multiprocessing.get_context("spawn")
        ctx.Process(target=self.spawn, args=(lock, self)).start()
        ctx.Process(target=child, args=(1,)).start()
""",
    b"""\
import asyncio, threading
def a():
    b()
def b():
    a()
    asyncio.ensure_future(None)
threading.Thread(target=a).start()
""",
    b"""\
import struct, zlib
_H = struct.Struct(">II")
def frame(p):
    crc = zlib.crc32(p) & 0xFFFFFFFF
    return _H.pack(len(p), crc) + p
""",
    b"""\
class ServerState:
    async def bad(self, uid, data):
        shard = self._shard_for_user(uid)
        registry = shard._sessions if uid else shard._challenges
        registry.pop(uid, None)
""",
    b"x = 1  # cpzk-lint: disable=THREAD-001,NO-SUCH-RULE -- stale on purpose\n",
]


def _seeds() -> list[bytes]:
    return list(_SEED_SNIPPETS)


def one_input(data: bytes) -> None:
    try:
        source = data.decode()
    except UnicodeDecodeError:
        source = data.decode("utf-8", "replace")
    # rotate the virtual path so plane-scoped rules all get exercised
    plane = ("core", "protocol", "server", "client", "ops", "")[len(data) % 6]
    path = f"cpzk_tpu/{plane}/fuzzed.py" if plane else "fuzzed.py"
    report = analyze_source(source, path=path)
    # the reporting path must hold too: render + JSON round-trip
    for f in report.findings + report.waived:
        assert f.render()
    json.dumps(report.to_dict())


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
