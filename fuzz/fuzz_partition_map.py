"""Fuzz target: partition-map parse/apply totality + routing invariants.

The partition map is the fleet's routing contract (ISSUE 11): every
daemon and client loads it from a file or the ops plane's
``/partitionmap`` body, so the parser is a trust boundary and routing
must be a **total function** over arbitrary user ids.

Invariants:
- ``PartitionMap.from_json`` / ``from_doc`` never raise anything but
  ``ValueError`` on arbitrary bytes/structures (parse totality);
- a map that parses is valid by construction: ranges disjoint AND
  exhaustive over the hash space, so ``partition_for`` answers exactly
  one partition for EVERY user id (routing totality), and the answer
  agrees with the owning partition's own ranges;
- serialization round-trips: ``from_json(to_json(m))`` reproduces the
  same version, digest, and routing;
- ``split`` is version-monotonic (+1), produces a map that is again
  disjoint + exhaustive, moves ONLY users from the split partition to
  the new one (every other id keeps its owner), and the moved set is
  exactly the ids hashing into the returned moved ranges — the property
  the live split flow's copy/drain stages rest on.

Run: python fuzz/fuzz_partition_map.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

import json
import random

from common import run_fuzzer

from cpzk_tpu.fleet.partition_map import (
    HASH_SPACE,
    PartitionMap,
    user_hash,
)


def _seeds() -> list[bytes]:
    m1 = PartitionMap.uniform(["a:1"])
    m3 = PartitionMap.uniform(["a:1", "b:2", "c:3"])
    m4, _ = m3.split(1, "d:4")
    return [
        m1.to_json().encode(),
        m3.to_json().encode(),
        m4.to_json().encode(),
        b"{}",
        b"[1,2,3]",
        json.dumps({"schema": "cpzk-partition-map/1", "version": 1,
                    "partitions": []}).encode(),
    ]


def _user_ids(rng: random.Random, data: bytes) -> list[str]:
    """Arbitrary user ids derived from the input: raw decodes, slices,
    and random unicode — routing must be total over all of them."""
    ids = [
        data.decode("utf-8", "replace")[:64],
        data.decode("latin-1")[:64],
        "",
        "u" * 300,
    ]
    for _ in range(8):
        n = rng.randint(0, 24)
        ids.append("".join(chr(rng.randint(1, 0x10FFF)) for _ in range(n)))
    return ids


def _check_routing(pmap: PartitionMap, ids: list[str]) -> None:
    for uid in ids:
        p = pmap.partition_for(uid)
        h = user_hash(uid)
        assert p.covers(h), "owner's ranges do not cover the id's hash"
        owners = [q.index for q in pmap.partitions if q.covers(h)]
        assert owners == [p.index], "id covered by more than one partition"


def _check_tiling(pmap: PartitionMap) -> None:
    spans = sorted(
        (lo, hi) for p in pmap.partitions for lo, hi in p.ranges
    )
    cursor = 0
    for lo, hi in spans:
        assert lo == cursor, "ranges overlap or gap"
        cursor = hi
    assert cursor == HASH_SPACE, "ranges do not exhaust the hash space"


def one_input(data: bytes) -> None:
    rng = random.Random(len(data) ^ (data[0] if data else 0))

    # 1. parse totality: only ValueError may escape
    pmap = None
    try:
        pmap = PartitionMap.from_json(data)
    except ValueError:
        pass
    if pmap is None:
        return

    # 2. a parsed map is valid: tiling + routing totality
    _check_tiling(pmap)
    ids = _user_ids(rng, data)
    _check_routing(pmap, ids)

    # 3. serialization round-trip: version/digest/routing stable
    again = PartitionMap.from_json(pmap.to_json())
    assert again.version == pmap.version
    assert again.digest == pmap.digest
    for uid in ids:
        assert (
            again.partition_for(uid).index == pmap.partition_for(uid).index
        )

    # 4. split: version monotonic, disjoint+exhaustive, ownership moves
    #    exactly for the moved ranges
    source = rng.randrange(len(pmap.partitions))
    try:
        new_map, moved = pmap.split(source, "new:9")
    except ValueError:
        return  # unsplittable (single-point range): a legitimate refusal
    assert new_map.version == pmap.version + 1
    _check_tiling(new_map)
    new_index = len(pmap.partitions)
    assert new_map.partitions[new_index].ranges == moved
    for uid in ids:
        before = pmap.partition_for(uid).index
        after = new_map.partition_for(uid).index
        in_moved = any(lo <= user_hash(uid) < hi for lo, hi in moved)
        if in_moved:
            assert before == source and after == new_index
        else:
            assert after == before, "split moved an id outside its ranges"


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
