"""Shared fuzz driver.

Twin of the reference's cargo-fuzz harnesses (``fuzz/fuzz_targets/*.rs``) —
which, per SURVEY.md §2.1 #21, no longer compile against the reference's own
v1.0.0 API; these stay runnable in CI by design.

Uses Atheris (libFuzzer for Python) when importable; otherwise falls back to
a built-in seeded mutation engine: byte flips, truncations, insertions,
splices, and length-field tampering over a seed corpus, plus pure random
blobs.  Deterministic under --seed, time- or run-bounded, exits nonzero on
the first invariant violation with the reproducing input hex-dumped.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mutate(rng: random.Random, data: bytes) -> bytes:
    buf = bytearray(data)
    for _ in range(rng.randint(1, 4)):
        op = rng.randrange(6)
        if op == 0 and buf:  # bit flip
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        elif op == 1 and buf:  # byte set
            buf[rng.randrange(len(buf))] = rng.randrange(256)
        elif op == 2 and buf:  # truncate
            del buf[rng.randrange(len(buf)):]
        elif op == 3:  # insert
            i = rng.randrange(len(buf) + 1)
            buf[i:i] = bytes(rng.randrange(256) for _ in range(rng.randint(1, 8)))
        elif op == 4 and len(buf) >= 8:  # length-field tamper (u32 BE)
            i = rng.randrange(len(buf) - 4)
            buf[i : i + 4] = rng.randrange(2**32).to_bytes(4, "big")
        elif op == 5 and buf:  # splice with random block
            i = rng.randrange(len(buf))
            j = min(len(buf), i + rng.randint(1, 16))
            buf[i:j] = os.urandom(j - i)
    return bytes(buf)


def run_bounded(one_input, seeds: list[bytes], runs: int = 0,
                seconds: float = 15.0, seed: int = 0) -> int:
    """Built-in engine, bounded by ``runs`` (when nonzero) or ``seconds``.
    Deterministic under a fixed ``seed`` apart from the raw-random-blob
    draws.  Returns the number of executions; raises on the first
    invariant violation with the reproducing input hex-dumped.  This is
    the entry point the CI fuzz-smoke tests drive directly (Atheris, when
    installed, would ignore bounds and fuzz forever)."""
    rng = random.Random(seed)
    corpus = list(seeds) + [b"", b"\x01", os.urandom(109)]
    deadline = time.monotonic() + seconds
    done = 0
    while (runs and done < runs) or (not runs and time.monotonic() < deadline):
        if rng.random() < 0.15:
            data = os.urandom(rng.randint(0, 160))
        else:
            data = _mutate(rng, rng.choice(corpus))
        try:
            one_input(data)
        except Exception:
            print(f"INVARIANT VIOLATION after {done} runs", file=sys.stderr)
            print("input:", data.hex(), file=sys.stderr)
            raise
        done += 1
    return done


def run_fuzzer(one_input, seeds: list[bytes], argv=None) -> None:
    """Drive ``one_input(data: bytes)``; Atheris when present, else the
    built-in engine.  ``one_input`` must raise only on invariant violations
    (expected parse failures are part of the harness)."""
    try:
        import atheris  # type: ignore

        atheris.Setup([sys.argv[0]], one_input)
        atheris.Fuzz()
        return
    except ImportError:
        pass

    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=0, help="0 = until --seconds")
    ap.add_argument("--seconds", type=float, default=15.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    runs = run_bounded(one_input, seeds, runs=args.runs,
                       seconds=args.seconds, seed=args.seed)
    print(f"ok: {runs} runs, no invariant violations")
