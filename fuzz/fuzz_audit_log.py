"""Fuzz target: proof-log scanning + audit fold-state invariants.

Arbitrary bytes presented as a proof log — truncated, tampered,
reordered, duplicated, or pure garbage — must leave the bulk audit
pipeline's parse-and-fold layer in a sane state (ISSUE 9 satellite):

Invariants:
- ``scan_records`` never raises; its valid-prefix offset is a byte
  offset within the input; every parsed record is a dict with an int,
  strictly increasing ``seq`` and a str ``type`` (the WAL prefix
  contract inherited byte-for-byte);
- scanning is **split-resume equivalent**: resuming from any prefix's
  (offset, prev_seq) cursor yields exactly the whole-buffer scan's
  suffix — the property SIGKILL-resume correctness rests on;
- ``validate_proof_record`` never raises and is total over arbitrary
  parsed JSON;
- the :class:`~cpzk_tpu.audit.pipeline.AuditState` fold never raises,
  its cursor offset/seq stay monotonic, its totals stay consistent
  (``records == audited + skipped``, ``audited == verified +
  rejected``), and the digest chain is split-independent: folding the
  records in one pass equals folding them across any resume boundary
  (cursor round-trip included).

The fold runs WITHOUT the crypto engine (outcomes derived from the
recorded verdict): the invariants under test are parsing, cursor, and
totals discipline — re-verification correctness is pinned by
``tests/test_audit.py`` against real proofs.

Run: python fuzz/fuzz_audit_log.py [--seconds 15] [--seed 0]
"""

from __future__ import annotations

import random

from common import run_fuzzer

from cpzk_tpu.audit.log import proof_record, validate_proof_record
from cpzk_tpu.audit.pipeline import (
    OUTCOME_REJECTED,
    OUTCOME_SKIPPED,
    OUTCOME_VERIFIED,
    AuditState,
)
from cpzk_tpu.audit import scan_records
from cpzk_tpu.durability.wal import HEADER_BYTES, _HEADER, encode_record


def _seeds() -> list[bytes]:
    frames = []
    seq = 0
    for i in range(4):
        seq += 1
        rec = proof_record(
            f"user-{i}", b"\x11" * 32, b"\x22" * 32, bytes([i]) * 32,
            b"\x03" * 109, i % 2 == 0, now=1,
        )
        rec["seq"] = seq
        rec["type"] = "proof"
        frames.append(encode_record(rec))
    seq += 1
    frames.append(encode_record({"seq": seq, "type": "register_user",
                                 "user_id": "x"}))
    whole = b"".join(frames)
    return [whole, whole[: len(whole) // 2], frames[0] * 3]


def _outcome(rec: dict) -> bytes:
    """Deterministic stand-in for the verification engine: well-formed
    records audit to their recorded verdict, everything else skips."""
    if validate_proof_record(rec) is not None:
        return OUTCOME_SKIPPED
    return OUTCOME_VERIFIED if rec["v"] else OUTCOME_REJECTED


def _fold(records, offsets, state: AuditState) -> AuditState:
    prev_offset = state.offset
    prev_records = state.records
    for rec, end in zip(records, offsets):
        outcome = _outcome(rec)
        state.note(rec, outcome, mismatch=outcome == OUTCOME_REJECTED)
        state.offset = end
        assert state.offset >= prev_offset, "cursor offset went backwards"
        prev_offset = state.offset
    assert state.records == prev_records + len(records)
    return state


def _frame_ends(buf: bytes, start: int, n: int) -> list[int]:
    out = []
    off = start
    for _ in range(n):
        length, _crc = _HEADER.unpack_from(buf, off)
        off += HEADER_BYTES + length
        out.append(off)
    return out


def one_input(data: bytes) -> None:
    records, valid = scan_records(data)
    assert 0 <= valid <= len(data)
    prev = None
    for rec in records:
        assert isinstance(rec, dict)
        seq = rec["seq"]
        assert isinstance(seq, int) and not isinstance(seq, bool)
        assert prev is None or seq > prev
        prev = seq
        assert isinstance(rec["type"], str)
        validate_proof_record(rec)  # total: must never raise

    ends = _frame_ends(data, 0, len(records))
    assert not ends or ends[-1] == valid

    # one-pass fold
    one = _fold(records, ends, AuditState())
    totals_hold(one)

    # split-resume fold at a pseudo-random frame boundary, with a cursor
    # round-trip at the seam (exactly what SIGKILL resume does)
    split = random.Random(len(data) ^ valid).randint(0, len(records))
    head = _fold(records[:split], ends[:split], AuditState())
    cur = head.to_cursor("fuzz.log")
    resumed = AuditState.from_cursor(cur, "fuzz.log")
    tail_records, tail_valid = scan_records(
        data, offset=resumed.offset, prev_seq=resumed.prev_seq
    )
    assert tail_records == records[split:], "split-resume scan diverged"
    assert tail_valid == valid
    two = _fold(tail_records, ends[split:], resumed)
    totals_hold(two)
    assert two.chain == one.chain, "digest chain is split-dependent"
    assert two.records == one.records
    assert (two.verified, two.rejected, two.skipped, two.mismatched) == (
        one.verified, one.rejected, one.skipped, one.mismatched
    )


def totals_hold(state: AuditState) -> None:
    assert state.records == state.audited + state.skipped
    assert state.audited == state.verified + state.rejected
    assert 0 <= state.mismatched <= state.audited


if __name__ == "__main__":
    run_fuzzer(one_input, _seeds())
