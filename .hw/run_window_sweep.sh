#!/bin/sh
# CPZK_MSM_WINDOW calibration sweep at N=16384, pippenger kernel only
# (model picks c=13 at m=4*16384+2; bracket it).  One bench.py run per
# window; persistent compile cache means each (shape, window) compiles
# once ever.  Usage: sh .hw/run_window_sweep.sh [windows...]
set -x
cd "$(dirname "$0")/.."
for c in "${@:-11 12 13 14 15}"; do
  for w in $c; do
    CPZK_BENCH_N=16384 CPZK_BENCH_KERNEL=pippenger CPZK_BENCH_ITERS=3 \
      CPZK_MSM_WINDOW=$w timeout 1800 python bench.py \
      > .hw/win_$w.json 2> .hw/win_$w.err
  done
done
