#!/bin/sh
# Tunnel heal-watcher (round 5).  Probes the axon TPU every ~3 min; on
# heal, runs the full measurement sequence with the crash-hardened
# bench.py (kernel lines survive child failures).  Artifacts land in
# .hw/ under benches/calibrate.py's expected names; timeline in
# .hw/sweep.log.  `touch .hw/LOCK` pauses the watcher (interactive TPU
# session); it exits once every measurement holds a REAL device record
# (guards demand a metric line without an "error" key — bench headers
# and 0.0 diagnostic/error records don't count).
cd "$(dirname "$0")" || exit 1
mkdir -p .hw
log() { echo "$(date -u +%H:%M:%S) $*" >> .hw/sweep.log; }
probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; (jnp.zeros((8,))+1).block_until_ready()" \
    >/dev/null 2>&1
}
has_tpu_bench() { grep -q '"plane": "tpu"' "$1" 2>/dev/null; }
# a real measurement: the metric line exists AND is not an error record
has_metric() { grep "$2" "$1" 2>/dev/null | grep -qv '"error"'; }
has_trace() { find .hw/xprof -name '*.xplane.pb' 2>/dev/null | grep -q .; }
all_done() {
  has_tpu_bench .hw/bench_16k.json && has_tpu_bench .hw/bench_64k.json \
    && has_metric .hw/k64_mul.jsonl field_mul_schoolbook \
    && has_metric .hw/k64_point.jsonl point_add \
    && has_metric .hw/k64_challenge.jsonl challenge_device \
    && has_metric .hw/point_pallas.json point_add \
    && has_tpu_bench .hw/win_13.json \
    && has_metric .hw/cross_1024.json verify_ \
    && has_trace \
    && has_metric .hw/e2e_curve_tpu.json '"backend": "tpu"'
}
log "watcher start (pid $$)"
while :; do
  if all_done; then log "ALL measurements landed; watcher exiting"; exit 0; fi
  if [ -e .hw/LOCK ]; then log "paused (LOCK)"; sleep 180; continue; fi
  if probe; then
    log "tunnel ALIVE - starting sweep"
    # 1. headline bench at 16k (+ e2e artifact, preserved aside only on
    # success so a failed retry can't snapshot another tier's e2e data)
    has_tpu_bench .hw/bench_16k.json || {
      CPZK_BENCH_N=16384 CPZK_BENCH_E2E=1 CPZK_BENCH_ITERS=3 \
      CPZK_BENCH_DEADLINE_SECS=1700 CPZK_BENCH_GUARD_SECS=800 \
        timeout 1800 python bench.py > .hw/bench_16k.json 2>> .hw/sweep.log
      has_tpu_bench .hw/bench_16k.json && \
        cp -f BENCH_E2E.json .hw/e2e_16k.json 2>/dev/null
      log "bench_16k: $(cat .hw/bench_16k.json)"; }
    probe || { log "wedged after bench_16k"; continue; }
    # 2. 64k tier (its auto run rewrites BENCH_E2E.json; 16k copy kept)
    has_tpu_bench .hw/bench_64k.json || {
      CPZK_BENCH_N=65536 CPZK_BENCH_E2E=1 CPZK_BENCH_ITERS=3 \
      CPZK_BENCH_DEADLINE_SECS=2300 CPZK_BENCH_GUARD_SECS=1100 \
        timeout 2400 python bench.py > .hw/bench_64k.json 2>> .hw/sweep.log
      has_tpu_bench .hw/bench_64k.json && \
        cp -f BENCH_E2E.json .hw/e2e_64k.json 2>/dev/null
      log "bench_64k: $(cat .hw/bench_64k.json)"; }
    probe || { log "wedged after bench_64k"; continue; }
    # 3. kernel A/Bs at 64k — each sub-file retried until it holds its
    # own measurement line (a wedge mid-trio must not freeze the rest)
    has_metric .hw/k64_mul.jsonl field_mul_schoolbook || {
      timeout 2400 python benches/bench_kernels.py --n 65536 --iters 3 \
        --only mul > .hw/k64_mul.jsonl 2>> .hw/sweep.log
      log "k64_mul: $(grep field_mul .hw/k64_mul.jsonl | tr '\n' ' ')"; }
    probe || { log "wedged after k64 mul"; continue; }
    has_metric .hw/k64_point.jsonl point_add || {
      timeout 2400 python benches/bench_kernels.py --n 65536 --iters 3 \
        --only point > .hw/k64_point.jsonl 2>> .hw/sweep.log
      log "k64_point: $(grep point_ .hw/k64_point.jsonl | tr '\n' ' ')"; }
    probe || { log "wedged after k64 point"; continue; }
    has_metric .hw/k64_challenge.jsonl challenge_device || {
      timeout 1200 python benches/bench_kernels.py --n 65536 --iters 3 \
        --only challenge > .hw/k64_challenge.jsonl 2>> .hw/sweep.log
      log "k64_challenge done"; }
    cat .hw/k64_*.jsonl > .hw/r5_kernels_64k.jsonl 2>/dev/null
    probe || { log "wedged after kernels_64k"; continue; }
    # 4. pallas point A/B (calibrate.py reads point_pallas.json)
    has_metric .hw/point_pallas.json point_add || {
      CPZK_PALLAS=1 timeout 1800 python benches/bench_kernels.py --n 16384 \
        --iters 3 --only point > .hw/point_pallas.json 2>> .hw/sweep.log
      log "point_pallas: $(grep point_ .hw/point_pallas.json | tr '\n' ' ')"; }
    probe || { log "wedged after pallas"; continue; }
    # 5. window sweep at 16k, pippenger (most-informative windows first)
    for w in 12 13 14 15 11; do
      has_tpu_bench .hw/win_$w.json && continue
      CPZK_BENCH_N=16384 CPZK_BENCH_KERNEL=pippenger CPZK_BENCH_ITERS=3 \
      CPZK_MSM_WINDOW=$w CPZK_BENCH_DEADLINE_SECS=0 \
        timeout 1500 python bench.py > .hw/win_$w.json 2>> .hw/sweep.log
      log "win_$w: $(cat .hw/win_$w.json)"
      probe || break
    done
    probe || { log "wedged during window sweep"; continue; }
    # 6. crossover point at 1k
    has_metric .hw/cross_1024.json verify_ || {
      timeout 1500 python benches/bench_kernels.py --n 1024 --verify-n 1024 \
        --iters 3 --only verify > .hw/cross_1024.json 2>> .hw/sweep.log
      log "cross_1024: $(grep verify_ .hw/cross_1024.json | tr '\n' ' ')"; }
    probe || { log "wedged before xprof"; continue; }
    # 7. one xprof trace of the winning kernel (steady-state, no compile);
    # retried until a real .xplane.pb lands (a killed run leaves only the
    # directory skeleton)
    has_trace || {
      rm -rf .hw/xprof
      timeout 1200 python benches/capture_xprof.py --n 4096 \
        --kernel rowcombined --outdir .hw/xprof >> .hw/sweep.log 2>&1
      if has_trace; then log "xprof captured"; else log "xprof FAILED"; fi; }
    probe || { log "wedged before e2e curve"; continue; }
    # 8. serving curve against the REAL device backend (gRPC -> batcher ->
    # TPU) — the north-star configuration, never before measured
    has_metric .hw/e2e_curve_tpu.json '"backend": "tpu"' || {
      timeout 1800 python benches/bench_e2e_curve.py --ns 4096 \
        --backend tpu > .hw/e2e_curve_tpu.json 2>> .hw/sweep.log
      log "e2e_curve_tpu: $(cat .hw/e2e_curve_tpu.json | tr '\n' ' ')"; }
  else
    log "wedged"
  fi
  sleep 150
done
