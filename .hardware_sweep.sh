#!/bin/sh
# Tunnel heal-watcher (round 5).  Probes the axon TPU every ~3 min; on
# heal, runs the full measurement sequence with the crash-hardened
# bench.py (kernel lines survive child failures).  Artifacts land in
# .hw/ under benches/calibrate.py's expected names; timeline in
# .hw/sweep.log.  A lockfile stops it from contending with an
# interactive TPU session: `touch .hw/LOCK` pauses the watcher.
cd "$(dirname "$0")" || exit 1
mkdir -p .hw
log() { echo "$(date -u +%H:%M:%S) $*" >> .hw/sweep.log; }
probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; (jnp.zeros((8,))+1).block_until_ready()" \
    >/dev/null 2>&1
}
log "watcher start (pid $$)"
while :; do
  if [ -e .hw/LOCK ]; then log "paused (LOCK)"; sleep 180; continue; fi
  if [ -e .hw/SWEEP_DONE ]; then log "sweep complete; watcher exiting"; exit 0; fi
  if probe; then
    log "tunnel ALIVE - starting sweep"
    # 1. headline bench at 16k (+ e2e artifact)
    [ -s .hw/bench_16k.json ] && grep -q '"plane": "tpu"' .hw/bench_16k.json || {
      CPZK_BENCH_N=16384 CPZK_BENCH_E2E=1 CPZK_BENCH_ITERS=3 \
      CPZK_BENCH_DEADLINE_SECS=1700 CPZK_BENCH_GUARD_SECS=800 \
        timeout 1800 python bench.py > .hw/bench_16k.json 2>> .hw/sweep.log
      log "bench_16k: $(cat .hw/bench_16k.json)"; }
    probe || { log "wedged after bench_16k"; continue; }
    # 2. 64k tier
    [ -s .hw/bench_64k.json ] && grep -q '"plane": "tpu"' .hw/bench_64k.json || {
      CPZK_BENCH_N=65536 CPZK_BENCH_ITERS=3 \
      CPZK_BENCH_DEADLINE_SECS=2300 CPZK_BENCH_GUARD_SECS=1100 \
        timeout 2400 python bench.py > .hw/bench_64k.json 2>> .hw/sweep.log
      log "bench_64k: $(cat .hw/bench_64k.json)"; }
    probe || { log "wedged after bench_64k"; continue; }
    # 3. kernel A/Bs at 64k (mul/point/challenge)
    [ -s .hw/r5_kernels_64k.jsonl ] || {
      timeout 2400 python benches/bench_kernels.py --n 65536 --iters 3 \
        --only mul > .hw/k64_mul.jsonl 2>> .hw/sweep.log
      timeout 2400 python benches/bench_kernels.py --n 65536 --iters 3 \
        --only point > .hw/k64_point.jsonl 2>> .hw/sweep.log
      timeout 1200 python benches/bench_kernels.py --n 65536 --iters 3 \
        --only challenge > .hw/k64_challenge.jsonl 2>> .hw/sweep.log
      cat .hw/k64_*.jsonl > .hw/r5_kernels_64k.jsonl
      log "kernels_64k done"; }
    probe || { log "wedged after kernels_64k"; continue; }
    # 4. pallas point A/B
    [ -s .hw/point_pallas.json ] || {
      CPZK_PALLAS=1 timeout 1800 python benches/bench_kernels.py --n 16384 \
        --iters 3 --only point > .hw/point_pallas.json 2>> .hw/sweep.log
      log "point_pallas: $(cat .hw/point_pallas.json)"; }
    probe || { log "wedged after pallas"; continue; }
    # 5. window sweep at 16k, pippenger
    for w in 12 13 14 15 11; do
      [ -s .hw/win_$w.json ] && grep -q '"plane": "tpu"' .hw/win_$w.json && continue
      CPZK_BENCH_N=16384 CPZK_BENCH_KERNEL=pippenger CPZK_BENCH_ITERS=3 \
      CPZK_MSM_WINDOW=$w CPZK_BENCH_DEADLINE_SECS=0 \
        timeout 1500 python bench.py > .hw/win_$w.json 2>> .hw/sweep.log
      log "win_$w: $(cat .hw/win_$w.json)"
      probe || break
    done
    probe || { log "wedged during window sweep"; continue; }
    # 6. crossover point at 1k
    [ -s .hw/cross_1024.json ] || {
      timeout 1500 python benches/bench_kernels.py --n 1024 --verify-n 1024 \
        --iters 3 --only verify > .hw/cross_1024.json 2>> .hw/sweep.log
      log "cross_1024 done"; }
    if [ -s .hw/bench_16k.json ] && [ -s .hw/bench_64k.json ] \
       && [ -s .hw/r5_kernels_64k.jsonl ] && [ -s .hw/win_13.json ]; then
      touch .hw/SWEEP_DONE; log "ALL measurements landed; exiting"; exit 0
    fi
  else
    log "wedged"
  fi
  sleep 150
done
