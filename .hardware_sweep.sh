#!/bin/sh
# Tunnel heal-watcher (round 5, revision 2: post lane-chunking fix).
# Probes the axon TPU every ~3 min; on heal, runs the measurement
# sequence with the crash-hardened bench.py (kernel lines survive child
# failures).  Artifacts land in .hw/; timeline in .hw/sweep.log.
# `touch .hw/LOCK` pauses the watcher (interactive TPU session); it
# exits once every gate below holds a REAL device record.
#
# Revision-2 changes: the >33k-lane monolith miscompile is worked
# around by the chunked dispatch (ops/backend.py LANE_CHUNK), so
# bench_64k is expected to PASS now and runs FIRST; bench_16k_v2
# re-measures the 16k tier under the shipped chunked dispatch (the
# committed bench_16k.json is the old 16386-lane monolith number);
# the pippenger window sweep runs last (single-device dispatch no
# longer uses pippenger — the sweep only calibrates the mesh path).
cd "$(dirname "$0")" || exit 1
mkdir -p .hw
log() { echo "$(date -u +%H:%M:%S) $*" >> .hw/sweep.log; }
probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; (jnp.zeros((8,))+1).block_until_ready()" \
    >/dev/null 2>&1
}
has_tpu_bench() { grep -q '"plane": "tpu"' "$1" 2>/dev/null; }
# a real measurement: the metric line exists AND is not an error record
has_metric() { grep "$2" "$1" 2>/dev/null | grep -qv '"error"'; }
has_trace() { find .hw/xprof -name '*.xplane.pb' 2>/dev/null | grep -q .; }
all_done() {
  has_tpu_bench .hw/bench_64k.json \
    && has_tpu_bench .hw/bench_16k_v2.json \
    && has_metric .hw/e2e_curve_tpu_v2.json '"backend": "tpu"' \
    && has_tpu_bench .hw/pallas_4k.json \
    && has_tpu_bench .hw/win_13.json \
    && has_trace
}
log "watcher start rev2 (pid $$)"
while :; do
  if all_done; then log "ALL measurements landed; watcher exiting"; exit 0; fi
  if [ -e .hw/LOCK ]; then log "paused (LOCK)"; sleep 180; continue; fi
  if probe; then
    log "tunnel ALIVE - starting sweep rev2"
    # 1. the 64k tier — first full-scale run of the chunked dispatch
    has_tpu_bench .hw/bench_64k.json || {
      CPZK_BENCH_N=65536 CPZK_BENCH_E2E=1 CPZK_BENCH_ITERS=3 \
      CPZK_BENCH_DEADLINE_SECS=2300 CPZK_BENCH_GUARD_SECS=1100 \
        timeout 2400 python bench.py > .hw/bench_64k.json 2>> .hw/sweep.log
      has_tpu_bench .hw/bench_64k.json && \
        cp -f BENCH_E2E.json .hw/e2e_64k.json 2>/dev/null
      log "bench_64k: $(cat .hw/bench_64k.json)"; }
    probe || { log "wedged after bench_64k"; continue; }
    # 2. 16k tier under the shipped chunked dispatch
    has_tpu_bench .hw/bench_16k_v2.json || {
      CPZK_BENCH_N=16384 CPZK_BENCH_E2E=1 CPZK_BENCH_ITERS=3 \
      CPZK_BENCH_DEADLINE_SECS=1700 CPZK_BENCH_GUARD_SECS=800 \
        timeout 1800 python bench.py > .hw/bench_16k_v2.json 2>> .hw/sweep.log
      has_tpu_bench .hw/bench_16k_v2.json && \
        cp -f BENCH_E2E.json .hw/e2e_16k_v2.json 2>/dev/null
      log "bench_16k_v2: $(cat .hw/bench_16k_v2.json)"; }
    probe || { log "wedged after bench_16k_v2"; continue; }
    # 3. serving curve against the device backend (first run recorded
    # 205 proofs/s gRPC vs 9,440 direct at 4k — re-measure after the
    # serving-side fixes land; artifact name versioned so the original
    # evidence survives)
    has_metric .hw/e2e_curve_tpu_v2.json '"backend": "tpu"' || {
      CPZK_BATCH_DEBUG=1 timeout 1800 python benches/bench_e2e_curve.py \
        --ns 4096 --backend tpu > .hw/e2e_curve_tpu_v2.json \
        2> .hw/e2e_curve_tpu_v2.err
      tail -40 .hw/e2e_curve_tpu_v2.err >> .hw/sweep.log
      log "e2e_curve_tpu_v2: $(cat .hw/e2e_curve_tpu_v2.json | tr '\n' ' ')"; }
    probe || { log "wedged after e2e_curve_v2"; continue; }
    # 4. xprof trace (have one from rev1; re-check in case it was lost)
    has_trace || {
      rm -rf .hw/xprof
      timeout 1200 python benches/capture_xprof.py --n 4096 \
        --kernel rowcombined --outdir .hw/xprof >> .hw/sweep.log 2>&1
      if has_trace; then log "xprof captured"; else log "xprof FAILED"; fi; }
    # 4a. thread-dispatch latency probe (serving-collapse suspect): 2 min
    has_metric .hw/threadlat.json threadlat || {
      timeout 600 python benches/debug_pip16k.py --stage threadlat \
        > .hw/threadlat.json 2>> .hw/sweep.log
      log "threadlat: $(cat .hw/threadlat.json)"; }
    probe || { log "wedged after threadlat"; continue; }
    # 4b. pallas graduation A/B: in-kernel-asserted rowcombined with the
    # pallas point kernels, 4k (direct A/B vs the 24.7k XLA number) and
    # 64k (does explicit tiling sidestep the large-lane miscompile?)
    has_tpu_bench .hw/pallas_4k.json || {
      CPZK_PALLAS=1 CPZK_BENCH_N=4096 CPZK_BENCH_KERNEL=rowcombined \
      CPZK_BENCH_ITERS=3 CPZK_BENCH_DEADLINE_SECS=0 \
        timeout 1500 python bench.py > .hw/pallas_4k.json 2>> .hw/sweep.log
      log "pallas_4k: $(cat .hw/pallas_4k.json)"; }
    probe || { log "wedged after pallas_4k"; continue; }
    [ -e .hw/pallas_64k_mono.done ] || {
      CPZK_PALLAS=1 CPZK_LANE_CHUNK=1048576 CPZK_BENCH_N=65536 \
      CPZK_BENCH_KERNEL=rowcombined CPZK_BENCH_ITERS=3 \
      CPZK_BENCH_DEADLINE_SECS=0 \
        timeout 1800 python bench.py > .hw/pallas_64k_mono.json \
        2>> .hw/sweep.log
      # one attempt only (informative probe): an assert failure here just
      # means pallas does not sidestep the large-lane defect
      probe && touch .hw/pallas_64k_mono.done
      log "pallas_64k_mono: $(cat .hw/pallas_64k_mono.json)"; }
    probe || { log "wedged before window sweep"; continue; }
    # 5. pippenger window sweep at 16k (mesh-path calibration only now);
    # chunked dispatch should let these PASS where rev1 failed
    for w in 13 11 12 14 15; do
      has_tpu_bench .hw/win_$w.json && continue
      CPZK_BENCH_N=16384 CPZK_BENCH_KERNEL=pippenger CPZK_BENCH_ITERS=3 \
      CPZK_MSM_WINDOW=$w CPZK_BENCH_DEADLINE_SECS=0 \
        timeout 1500 python bench.py > .hw/win_$w.json 2>> .hw/sweep.log
      log "win_$w: $(cat .hw/win_$w.json)"
      probe || break
    done
  else
    log "wedged"
  fi
  sleep 150
done
