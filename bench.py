"""Benchmark: batched Chaum-Pedersen proof verification throughput.

Prints ONE JSON line:
    {"metric": "batch_verify_proofs_per_sec", "value": N, "unit": "proofs/s",
     "vs_baseline": R}

Baseline: the reference's honest CPU verification rate — ~159 us/proof
(~6289 proofs/s/core) per BASELINE.md; its batch fast path never engages
because of the RLC coefficient bug (SURVEY.md §3.2), so single-proof
verification is the reference's true throughput.

The timed region is the device compute of the per-proof verification kernel
(ground-truth path — every proof individually checked on-device). Challenge
derivation and limb marshalling are host-side preparation, excluded here and
measured separately by the serving-path benchmarks (see benches/).
"""

from __future__ import annotations

import json
import time

N = 2048
ITERS = 5


def main() -> None:
    import jax
    import numpy as np

    from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops import curve, verify
    from cpzk_tpu.ops.backend import _points_soa, _windows

    rng = SecureRng()
    params = Parameters.new()

    # Build a small corpus of real proofs and tile it to N rows: group-op
    # cost on device is data-independent, so tiling does not flatter the
    # numbers, it only keeps host-side corpus generation out of the budget.
    corpus = 64
    rows = []
    for _ in range(corpus):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proof = prover.prove_with_transcript(rng, Transcript())
        t2 = Transcript()
        t2.append_parameters(
            Ristretto255.element_to_bytes(params.generator_g),
            Ristretto255.element_to_bytes(params.generator_h),
        )
        t2.append_statement(
            Ristretto255.element_to_bytes(prover.statement.y1),
            Ristretto255.element_to_bytes(prover.statement.y2),
        )
        t2.append_commitment(
            Ristretto255.element_to_bytes(proof.commitment.r1),
            Ristretto255.element_to_bytes(proof.commitment.r2),
        )
        rows.append((prover.statement, proof, t2.challenge_scalar()))

    reps = (N + corpus - 1) // corpus
    rows = (rows * reps)[:N]

    g = curve.points_to_device([params.generator_g.point])  # [20, 1], broadcasts
    h = curve.points_to_device([params.generator_h.point])
    y1 = _points_soa([st.y1.point for st, _, _ in rows], N)
    y2 = _points_soa([st.y2.point for st, _, _ in rows], N)
    r1 = _points_soa([pr.commitment.r1.point for _, pr, _ in rows], N)
    r2 = _points_soa([pr.commitment.r2.point for _, pr, _ in rows], N)
    ws = _windows([pr.response.s.value for _, pr, _ in rows], N)
    wc = _windows([c.value for _, _, c in rows], N)

    kernel = jax.jit(verify.verify_each_kernel)
    args = (g, h, y1, y2, r1, r2, ws, wc)

    out = jax.block_until_ready(kernel(*args))  # compile + warmup
    assert bool(np.asarray(out).all()), "bench corpus failed verification"

    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        jax.block_until_ready(kernel(*args))
        best = min(best, time.perf_counter() - t0)

    value = N / best
    baseline = 6289.0  # proofs/s, reference single-core CPU (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": "batch_verify_proofs_per_sec",
                "value": round(value, 1),
                "unit": "proofs/s",
                "vs_baseline": round(value / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
