"""Benchmark: batched Chaum-Pedersen proof verification throughput.

Prints ONE JSON line:
    {"metric": "batch_verify_proofs_per_sec", "value": N, "unit": "proofs/s",
     "vs_baseline": R}

Baseline: the reference's honest CPU verification rate — ~159 us/proof
(~6289 proofs/s/core) per BASELINE.md; its batch fast path never engages
because of the RLC coefficient bug (SURVEY.md §3.2), so single-proof
verification is the reference's true throughput.

The timed region is the device compute of the corrected-RLC combined batch
check (the accept path for an all-valid batch) — the north-star
configuration of BASELINE.md — via two interchangeable kernels:

- ``rowcombined``: per-row shared-doubling windowed chains + tree sum
  (``ops/verify.combined_kernel``), ~570 point-ops/row, compile-light;
- ``pippenger``: one windowed-Pippenger MSM over all 4N+2 terms
  (``ops/msm``), ~8*K point-adds/row amortized, compile-heavy.

``CPZK_BENCH_KERNEL=auto`` (default) runs each kernel in its own guarded
subprocess (``CPZK_BENCH_GUARD_SECS`` per kernel) — a pathological XLA
compile is an uninterruptible native call, so isolation (not signals) is
what guarantees a surviving measurement — and reports the faster of the
two.  Subprocesses run sequentially so they never contend for the device.

Host-side scalar prep (challenge derivation, alpha draws, digit recode) and
limb marshalling pipeline with device compute in the serving path; they are
measured separately by ``benches/bench_batch.py`` (end-to-end BatchVerifier
timings, batch-vs-individual curves, scaling over N).

Env knobs: CPZK_BENCH_N (default 16384 rows), CPZK_BENCH_ITERS (default 3),
CPZK_BENCH_KERNEL in {auto, rowcombined, pippenger}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

N = int(os.environ.get("CPZK_BENCH_N", "16384"))
ITERS = int(os.environ.get("CPZK_BENCH_ITERS", "3"))
KERNEL = os.environ.get("CPZK_BENCH_KERNEL", "auto")
GUARD_SECS = int(os.environ.get("CPZK_BENCH_GUARD_SECS", "1200"))
CORPUS = 64
BASELINE = 6289.0  # proofs/s, reference single-core CPU (BASELINE.md)
_E2E_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_E2E.json")

# Hard wall-clock ceiling for the whole auto run (round-3 lesson: the
# driver's window is finite and unknown; a bench that exceeds it records
# NOTHING, which is strictly worse than a diagnostic line).  Every probe
# and guard window below is clipped against this.  0 disables the
# ceiling (sweep runs own their budget via external `timeout`).
DEADLINE_SECS = int(os.environ.get("CPZK_BENCH_DEADLINE_SECS", "540"))
_T0 = time.monotonic()
_EMIT_LOCK = threading.Lock()
_EMITTED = False
# best kernel result collected so far, visible to the watchdog so a late
# wedge cannot discard an already-measured number
_BEST: float | None = None
_BEST_KERNEL: str | None = None


def _plane() -> str:
    """Plane of a kernel measurement: "tpu" on the real device, "host"
    when CPZK_BENCH_PLATFORM forced a CPU emulation run."""
    return "host" if os.environ.get("CPZK_BENCH_PLATFORM") else "tpu"


def _remaining() -> float:
    """Seconds left before the hard deadline (inf when disabled)."""
    if DEADLINE_SECS <= 0:
        return float("inf")
    return DEADLINE_SECS - (time.monotonic() - _T0)


def limbs_cols(points):
    """Host edwards points -> [4, 20, n] int32 (X/Y/Z/T limb columns)."""
    import numpy as np

    from cpzk_tpu.ops import limbs

    return np.stack(
        [limbs.ints_to_limbs([p[i] for p in points]) for i in range(4)]
    )


def identity_cols(k):
    """[4, 20, k] identity-point columns via the canonical helper."""
    import numpy as np

    from cpzk_tpu.ops import curve

    return np.stack([np.asarray(c) for c in curve.identity((k,))])


class _Inputs:
    """Corpus proofs tiled to N rows + host-side scalar prep."""

    def __init__(self):
        import numpy as np

        from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness  # noqa: F401
        from cpzk_tpu.core.ristretto import Ristretto255
        from cpzk_tpu.core.scalars import L

        rng = SecureRng()
        self.params = params = Parameters.new()

        # Real proofs, tiled: device group-op cost is data-independent, so
        # tiling does not flatter the numbers, it only keeps host-side
        # corpus generation out of the budget.  Every tiled row still gets
        # its own random alpha.
        from cpzk_tpu.core.transcript import derive_challenges_batch

        proofs = []
        for _ in range(CORPUS):
            prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
            proofs.append(
                (prover.statement, prover.prove_with_transcript(rng, Transcript()))
            )
        eb = Ristretto255.element_to_bytes
        challenges = derive_challenges_batch(
            [None] * CORPUS,
            [eb(params.generator_g)] * CORPUS,
            [eb(params.generator_h)] * CORPUS,
            [eb(st.y1) for st, _ in proofs],
            [eb(st.y2) for st, _ in proofs],
            [eb(pr.commitment.r1) for _, pr in proofs],
            [eb(pr.commitment.r2) for _, pr in proofs],
        )
        rows = [(st, pr, ch) for (st, pr), ch in zip(proofs, challenges)]
        self.proof_rows = proofs  # (statement, proof) pairs for the e2e pass

        reps = (N + CORPUS - 1) // CORPUS
        self.tile = lambda cols: np.tile(cols, (1, reps))[:, :N]
        self.r1c = limbs_cols([p.commitment.r1.point for _, p, _ in rows])
        self.y1c = limbs_cols([s.y1.point for s, _, _ in rows])
        self.r2c = limbs_cols([p.commitment.r2.point for _, p, _ in rows])
        self.y2c = limbs_cols([s.y2.point for s, _, _ in rows])
        self.gh = limbs_cols([params.generator_g.point, params.generator_h.point])

        self.a = [Ristretto255.random_scalar(rng).value for _ in range(N)]
        self.b = Ristretto255.random_scalar(rng).value
        self.c = [rows[i % CORPUS][2].value for i in range(N)]
        self.s = [rows[i % CORPUS][1].response.s.value for i in range(N)]
        self.ac = [x * y % L for x, y in zip(self.a, self.c)]
        self.ba = [self.b * x % L for x in self.a]
        self.bac = [self.b * x % L for x in self.ac]
        self.sum_as = sum(x * y for x, y in zip(self.a, self.s)) % L
        self.corr = [(L - self.sum_as) % L, (L - self.b * self.sum_as % L) % L]


def _time_kernel(fn, args) -> float:
    import jax

    ok = jax.block_until_ready(fn(*args))  # compile + warmup
    assert bool(ok), "bench batch failed the combined check"
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return N / best


def _pippenger_setup(inp: _Inputs):
    """Build device inputs + jitted kernel -> (fn, args); shared by the
    timed bench and the xprof capture (which must set up OUTSIDE its
    trace window)."""
    import numpy as np
    import jax.numpy as jnp

    from cpzk_tpu.ops import msm
    from cpzk_tpu.ops import backend as B
    from cpzk_tpu.ops.backend import _pad_pow2

    m_used = 4 * N + 2
    m = 4 * _pad_pow2(N) + 2
    c = msm.pick_window(m)
    # mirror the production dispatch (ops/backend._combined_pippenger):
    # past LANE_CHUNK the MSM runs as identical per-chunk programs whose
    # partial points tree-sum into one identity test
    m_pad = m if m <= B.LANE_CHUNK else B._pad_lanes(m)
    scalars = inp.a + inp.ac + inp.ba + inp.bac + inp.corr
    digits = msm.scalars_to_signed_digits(scalars + [0] * (m_pad - m_used), c)

    ident = identity_cols(m_pad - m_used)
    pts = tuple(
        jnp.asarray(
            np.concatenate(
                [inp.tile(inp.r1c[i]), inp.tile(inp.y1c[i]),
                 inp.tile(inp.r2c[i]), inp.tile(inp.y2c[i]),
                 inp.gh[i], ident[i]],
                axis=1,
            )
        )
        for i in range(4)
    )
    dig = jnp.asarray(digits)
    # the SHARED production dispatch (chunk schedule included): the bench
    # times exactly what TpuBackend serves
    return (lambda p, d: B.chunked_msm_identity(c, p, d)), (pts, dig)


def bench_pippenger(inp: _Inputs) -> float:
    fn, args = _pippenger_setup(inp)
    return _time_kernel(fn, args)


def bench_rowcombined(inp: _Inputs) -> float:
    fn, args = _rowcombined_setup(inp)
    return _time_kernel(fn, args)


def _rowcombined_setup(inp: _Inputs):
    import numpy as np
    import jax.numpy as jnp

    from cpzk_tpu.ops import backend as B

    # correction row is folded in as row N+1 (G with -sum(a s) in the r1
    # slot, H with -b sum(a s) in the y1 slot); identity rows pad to the
    # production lane schedule (ops/backend._pad_lanes): chunked past
    # LANE_CHUNK, mirroring TpuBackend.verify_combined.
    lanes = N + 1
    pad = B._pad_lanes(lanes)
    npad = pad - lanes
    ident = identity_cols(npad)          # post-correction padding rows
    identc = identity_cols(npad + 1)     # identity corr slot + padding

    # build per-slot arrays with the correction column appended
    r1 = tuple(
        jnp.asarray(np.concatenate(
            [inp.tile(inp.r1c[i]), inp.gh[i][:, :1], ident[i]], axis=1))
        for i in range(4)
    )
    y1 = tuple(
        jnp.asarray(np.concatenate(
            [inp.tile(inp.y1c[i]), inp.gh[i][:, 1:2], ident[i]], axis=1))
        for i in range(4)
    )
    r2 = tuple(
        jnp.asarray(np.concatenate(
            [inp.tile(inp.r2c[i]), identc[i]], axis=1))
        for i in range(4)
    )
    y2 = tuple(
        jnp.asarray(np.concatenate(
            [inp.tile(inp.y2c[i]), identc[i]], axis=1))
        for i in range(4)
    )

    from cpzk_tpu.ops.curve import scalars_to_windows

    zeros = [0] * npad
    w_a = jnp.asarray(scalars_to_windows(inp.a + [inp.corr[0]] + zeros))
    w_ac = jnp.asarray(scalars_to_windows(inp.ac + [inp.corr[1]] + zeros))
    w_ba = jnp.asarray(scalars_to_windows(inp.ba + [0] + zeros))
    w_bac = jnp.asarray(scalars_to_windows(inp.bac + [0] + zeros))

    # the SHARED production dispatch (chunk schedule included): the bench
    # times exactly what TpuBackend serves
    def fn(r1_, y1_, r2_, y2_, wa, wac, wba, wbac):
        return B.chunked_combined_identity(
            pad, r1_, y1_, r2_, y2_, wa, wac, wba, wbac)

    return fn, (r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)


def _emit(value: float, diagnostic: str | None = None,
          plane: str = "tpu", kernel: str | None = None) -> None:
    """``plane`` is machine-readable provenance (VERDICT r4 item 4): "tpu"
    for a real device measurement, "host" for a CPU-side rate (native
    fallback or a forced-CPU emulation run), "none" when the value is a
    0.0 placeholder.  Without it, consumers charting rounds can only tell
    a host number from a device number by parsing the free-text
    diagnostic."""
    global _EMITTED
    with _EMIT_LOCK:  # exactly one JSON line, main thread or watchdog
        if _EMITTED:
            return
        _EMITTED = True
    if value <= 0.0:
        plane = "none"
    rec = {
        "metric": "batch_verify_proofs_per_sec",
        "value": round(value, 1),
        "unit": "proofs/s",
        "vs_baseline": round(value / BASELINE, 3),
        "plane": plane,
    }
    if kernel:
        rec["kernel"] = kernel
    if diagnostic:
        rec["diagnostic"] = diagnostic
    print(json.dumps(rec), flush=True)


def _start_watchdog() -> None:
    """Guarantee one JSON line inside the deadline even if this process is
    stuck somewhere unforeseen: a daemon thread that force-emits at the
    deadline — the best kernel number collected so far if one exists
    (a late wedge must not discard a real measurement), else a 0.0
    diagnostic record — and exits the interpreter.  All device work
    happens in guarded subprocesses, so killing the parent here cannot
    corrupt a measurement — only forfeit one in progress."""
    if DEADLINE_SECS <= 0:
        return

    def _fire() -> None:
        slack = _remaining() - 10.0
        if slack > 0:
            time.sleep(slack)
        if _BEST is not None:
            _emit(_BEST, diagnostic="watchdog: deadline hit after this "
                  "kernel finished; a later stage was still running",
                  plane=_plane(), kernel=_BEST_KERNEL)
        else:
            _emit(0.0, diagnostic="watchdog: bench hit its "
                  f"{DEADLINE_SECS}s deadline before any kernel finished")
        sys.stdout.flush()
        os._exit(0)

    threading.Thread(target=_fire, daemon=True).start()


def _run_guarded(kernel: str, e2e: bool = False,
                 reserve: float = 0.0) -> float | None:
    """Run one kernel in a guarded subprocess; returns proofs/s or None.
    ``reserve`` is wall-clock held back for work scheduled after this
    kernel — the guard window is clipped to ``remaining - reserve`` so a
    slow first kernel cannot starve the deadline.  The e2e artifact pass
    runs in at most one child (the backend chooses its own combined-check
    path, so per-kernel e2e labels would imply a comparison that does not
    exist)."""
    guard = min(GUARD_SECS, _remaining() - reserve)
    if guard < 60:
        print(f"{kernel} bench skipped: only {guard:.0f}s of deadline left",
              file=sys.stderr)
        return None
    env = dict(os.environ, CPZK_BENCH_KERNEL=kernel,
               CPZK_BENCH_E2E="1" if e2e else "0",
               CPZK_BENCH_DEADLINE_SECS="0")

    def _e2e_stamp():
        """(mtime_ns, size) of the e2e artifact — detects whether the
        child wrote it (a child can write a real record and STILL die in
        native teardown; its record must survive the parent's cleanup).
        Sound because _write_e2e_record replaces atomically — a guard
        kill can never leave a half-written file behind."""
        try:
            st = os.stat(_E2E_PATH)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    stamp_before = _e2e_stamp() if e2e else None
    timed_out = False
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=guard,
        )
        stdout, stderr, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        # the kernel line may already be on the captured stdout (e.g. the
        # measurement finished and a later stage hung) — salvage it
        print(f"{kernel} bench timed out after {guard:.0f}s", file=sys.stderr)

        def _as_text(v) -> str:
            return v.decode(errors="replace") if isinstance(v, bytes) else (v or "")

        stdout, stderr, rc = _as_text(e.stdout), _as_text(e.stderr), -1
        timed_out = True
        if stderr:
            print(f"{kernel} child stderr tail:\n{stderr[-2000:]}",
                  file=sys.stderr)
    if rc != 0:
        if not timed_out:
            print(f"{kernel} bench exited rc={rc}:\n{stderr[-2000:]}",
                  file=sys.stderr)
        # A child that died (crash, signal, guard kill) with the artifact
        # untouched leaves a STALE record from a previous run — replace it
        # with a diagnostic.  But if the artifact changed, the child wrote
        # a real record (then died in teardown): keep it.
        if e2e and _e2e_stamp() == stamp_before:
            cause = (f"killed by the {guard:.0f}s guard" if timed_out
                     else f"died rc={rc}")
            _write_e2e_record(0.0, diagnostic=(
                f"e2e child {cause} before the artifact was written"))
    # Parse the LAST metric line on stdout regardless of exit status: a
    # child that measured the kernel and then died in a later stage (the
    # e2e pass, an emit-path wedge) must not lose the measurement
    # (round-5 lesson: a hardware window is too precious to discard a
    # number that was already printed).
    for line in reversed(stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
            v = float(rec["value"])
        except Exception:
            continue
        if v > 0.0:
            if rc != 0:
                print(f"{kernel}: salvaged measurement from failed child",
                      file=sys.stderr)
            return v
        break
    if rc == 0:
        print(f"{kernel} bench produced no JSON:\n{stdout[-500:]}",
              file=sys.stderr)
    return None


def _host_fallback_rate() -> tuple[float, int, bool]:
    """Host-plane batch verify -> (proofs/s, rows measured, native?): the
    honest this-machine number when no accelerator is reachable.  Pure
    host path — never touches jax, so it cannot hang on a wedged tunnel."""
    from cpzk_tpu import BatchVerifier, Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.protocol.batch import BatchEntry, CpuBackend

    from cpzk_tpu.core import _native

    # without the native core the pure-Python path runs ~ms/proof —
    # shrink the row count so one iteration fits well inside the deadline
    native = _native.load() is not None
    n_rows = N if native else min(N, 2048)

    rng = SecureRng()
    params = Parameters.new()
    proofs = []
    for _ in range(CORPUS):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proofs.append((prover.statement, prover.prove_with_transcript(rng, Transcript())))
    bv = BatchVerifier(backend=CpuBackend(), max_size=max(n_rows, 1000))
    for i in range(n_rows):
        st, pr = proofs[i % CORPUS]
        bv.entries.append(BatchEntry(params, st, pr, None))
    assert not any(r is not None for r in bv.verify(rng))  # untimed warmup
    best = float("inf")
    for _ in range(max(1, ITERS - 1)):
        t0 = time.perf_counter()
        results = bv.verify(rng)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        assert not any(r is not None for r in results)
        if _remaining() < 2 * dt + 45:  # leave room for the emit
            break
    return n_rows / best, n_rows, native


def _device_probe(timeout: float = 90) -> tuple[bool, str]:
    """One tiny device computation in a guarded subprocess: if the TPU
    tunnel is wedged, device *init* hangs forever — better to burn a
    probe window than a full guard window per kernel.  Returns
    (ok, failure_reason) so a hang is distinguishable from a
    deterministic error (broken install, PJRT failure)."""
    code = (
        "import jax, jax.numpy as jnp;"
        "(jnp.zeros((8,)) + 1).block_until_ready();"
        "print('ok')"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=dict(os.environ), capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe hung past {timeout:.0f}s (wedged tunnel)"
    if proc.returncode == 0:
        return True, ""
    return False, (
        f"probe exited rc={proc.returncode}: {proc.stderr.strip()[-500:]}"
    )


def _probe_with_backoff() -> tuple[bool, str]:
    """Retry the device probe briefly — wedges are usually hours-long, so
    a couple of attempts distinguishes "transient blip" from "wedged"
    and anything longer only eats the kernel budget (round-3 lesson: a
    30-min probe loop starved the whole artifact).  Budget:
    CPZK_BENCH_PROBE_SECS total (default 200s), clipped so at least
    ~300s of deadline survives for the kernels.  Returns
    (ok, last_failure_reason)."""
    budget = float(os.environ.get("CPZK_BENCH_PROBE_SECS", "200"))
    # leave ~300s of deadline for the kernels, but always probe at least
    # once (a floor of 45s) so the diagnostic reflects a real attempt
    budget = min(budget, max(_remaining() - 300, 45.0))
    deadline = time.monotonic() + budget
    attempt = 0
    reason = ""
    while True:
        attempt += 1
        window = deadline - time.monotonic()
        if window < 10:
            return False, reason or "no probe budget inside the deadline"
        ok, reason = _device_probe(timeout=min(90.0, window))
        if ok:
            if attempt > 1:
                print(f"device probe ok after {attempt} attempts", file=sys.stderr)
            return True, ""
        remaining = deadline - time.monotonic()
        if remaining <= 10:
            return False, reason
        wait = min(20.0, remaining)
        print(
            f"device probe failed (attempt {attempt}: {reason}); retrying in "
            f"{wait:.0f}s ({remaining:.0f}s of probe budget left)",
            file=sys.stderr,
        )
        time.sleep(wait)


def main() -> None:
    # CPZK_BENCH_PLATFORM=cpu forces the CPU backend for local smoke runs;
    # env vars alone don't reach jax's config (the axon sitecustomize
    # imports jax at interpreter startup), so apply it in-process.
    plat = os.environ.get("CPZK_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    # persistent XLA compile cache shared by sweep + driver runs: the
    # pippenger program's first compile is the single biggest risk to a
    # hardware window (minutes); pay it once per (shape, window) ever
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_bench_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:
        pass  # older jax without the knob: compile-cache is best-effort

    if KERNEL == "auto":
        _start_watchdog()
        # invalidate the PREVIOUS run's e2e record up front: the paths
        # that never spawn the e2e child (probe failure -> host fallback,
        # guard-window skip) must not leave a stale number reading as
        # this run's result; a successful e2e child overwrites this
        _write_e2e_record(0.0, diagnostic=(
            "e2e not measured this run (pre-run placeholder)"))
        if not plat:
            ok, reason = _probe_with_backoff()
            if not ok:
                # Record something machine-readable AND real: the native
                # host-plane batch verify rate at the same N (clearly
                # labeled — it is NOT a TPU measurement), falling back to
                # a 0.0 diagnostic only if even that fails.
                try:
                    v, n_rows, native = _host_fallback_rate()
                    path = "native" if native else "pure-Python"
                    _emit(v, diagnostic=(
                        "TPU unreachable through the whole probe budget "
                        f"(last failure: {reason}); value is the HOST-plane "
                        f"{path} batch verify rate at N={n_rows} on this "
                        "container, not a device measurement"),
                        plane="host", kernel=f"host-{path}")
                except Exception as e:  # noqa: BLE001 — artifact must land
                    _emit(0.0, diagnostic=f"device unreachable ({reason}); "
                          f"host fallback also failed: {e}")
                return
        # Sequential guarded subprocesses: no device contention, and a hung
        # native compile in one kernel cannot lose the other's number.
        # rowcombined goes first (compile-light → most likely to land a
        # number); it reserves a slice of deadline so the compile-heavy
        # pippenger still gets a chance, and an emit-worthy result exists
        # even if pippenger's window runs dry.
        global _BEST, _BEST_KERNEL
        results = {}
        v = _run_guarded("rowcombined", e2e=True,
                         reserve=min(180.0, _remaining() / 2))
        if v is not None:
            results["rowcombined"] = _BEST = v
            _BEST_KERNEL = "rowcombined"
        v = _run_guarded("pippenger", reserve=20.0)
        if v is not None:
            results["pippenger"] = v
            if v > (_BEST or 0.0):
                _BEST, _BEST_KERNEL = v, "pippenger"
        if not results:
            _emit(0.0, diagnostic="device reachable but no bench kernel "
                  "finished inside its guard window (wedge mid-run, or "
                  "compile exceeded the per-kernel budget)")
            return
        best = max(results, key=results.get)
        _emit(results[best], plane=_plane(), kernel=best)
        return

    inp = _Inputs()
    fn = {"rowcombined": bench_rowcombined, "pippenger": bench_pippenger}[KERNEL]
    _emit(fn(inp), plane=_plane(), kernel=KERNEL)
    if os.environ.get("CPZK_BENCH_E2E", "0") == "1":
        # best-effort second artifact: an e2e failure (wedge mid-run, a
        # backend-path bug) must never cost the kernel line already on
        # stdout — record the failure in the artifact instead
        try:
            _bench_e2e(inp)
        except Exception as e:  # noqa: BLE001 — diagnostic artifact
            _write_e2e_record(0.0, diagnostic=(
                f"e2e pass failed: {type(e).__name__}: {e}"))
            print(f"e2e pass failed (kernel line unaffected): {e}",
                  file=sys.stderr)


def _write_e2e_record(value: float, platform: str = "none",
                      diagnostic: str | None = None) -> None:
    """Overwrite BENCH_E2E.json with ONE uniform-schema record (the
    artifact holds the latest run; sweep history lives in .hw/).  Failure
    records carry the same keys as success records so consumers indexing
    vs_baseline/platform never KeyError on a failed round."""
    rec = {
        "metric": "batch_verify_e2e_proofs_per_sec",
        "value": round(value, 1),
        "unit": "proofs/s",
        "vs_baseline": round(value / BASELINE, 3),
        "n": N,
        "platform": platform,
    }
    if diagnostic:
        rec["diagnostic"] = diagnostic
    # atomic replace: a guard kill mid-write must never leave truncated
    # JSON (the parent's stamp check would then preserve the wreckage)
    tmp = _E2E_PATH + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(rec) + "\n")
    os.replace(tmp, _E2E_PATH)


def _bench_e2e(inp: _Inputs) -> None:
    """End-to-end serving-path rate (VERDICT r2 item 9): the kernel line
    above times device compute only, while the 6,289/s baseline is a full
    per-proof figure.  This measures challenge derivation (native merlin,
    threaded) + RLC scalar prep + window decomposition + limb marshalling
    + the device combined check for N rows, and OVERWRITES BENCH_E2E.json
    with one JSON line (a second artifact holding the latest run; stdout
    stays one-line, sweep history lives in .hw/)."""
    from cpzk_tpu import BatchVerifier, SecureRng
    from cpzk_tpu.ops.backend import TpuBackend

    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.protocol.batch import BatchEntry

    rng = SecureRng()
    bv = BatchVerifier(backend=TpuBackend(), max_size=N)
    for i in range(N):
        # reuse the corpus proofs without re-validating statements
        st, pr = inp.proof_rows[i % CORPUS]
        bv.entries.append(BatchEntry(inp.params, st, pr, None))

    def once() -> bool:
        rows = bv.prepare_rows(rng)
        beta = Ristretto255.random_scalar(rng)
        return bv.backend.verify_combined(rows, beta)

    if not once():  # warm (device compile already cached by the kernel run)
        raise RuntimeError(
            f"combined batch check rejected an all-valid batch at N={N} "
            "(backend path) — correctness regression, not a timing issue")
    best = float("inf")
    for _ in range(max(1, ITERS - 1)):
        t0 = time.perf_counter()
        ok = once()
        best = min(best, time.perf_counter() - t0)
        if not ok:
            raise RuntimeError("combined check flipped to reject mid-bench")
    import jax

    # provenance: a CPU-backend smoke number must never read as a TPU
    # result in the recorded artifact
    _write_e2e_record(N / best, platform=jax.devices()[0].platform)


if __name__ == "__main__":
    main()
